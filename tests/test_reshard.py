"""Elastic mesh resharding (parallel/reshard.py) + ElasticFitDriver.

What is asserted BIT-exact vs what carries a documented tolerance
(ARCHITECTURE.md § Elastic resharding):

- N→M→N flat-shard round trips, reshard-vs-unsharded-resume state, and
  the recovery machinery itself (checkpoint → reshard → resume vs a
  direct continuation over the SAME mesh sequence) are bit-exact —
  params, Adam slots, fault state and the dropout-RNG chain.
- Training the same batches on DIFFERENT device counts is NOT bit-equal
  (float reduction order over the data axis, ~1e-7 on this mesh); that
  is a property of data parallelism, not of the recovery path, and the
  drill comparator therefore replays the same mesh sequence.
"""

import json
import os
import zipfile

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data import DataSet, ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import flight
from deeplearning4j_tpu.parallel import reshard
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.zero import (
    build_layout,
    shard_model_opt_state,
)
from deeplearning4j_tpu.train import faults
from deeplearning4j_tpu.train.faults import (
    ElasticFitDriver,
    ElasticRecoveryExhaustedError,
    InjectedHostDropout,
    MeshFailureError,
    host_dropout_injection,
    is_mesh_failure,
)
from deeplearning4j_tpu.train.model_serializer import ModelSerializer
from deeplearning4j_tpu.updaters import Adam

N_IN, N_OUT = 5, 3


@pytest.fixture(autouse=True)
def _isolate_flight_recorder():
    """The default flight recorder is process-global; tests here mutate
    its dump_dir and ring — restore both so other suites' black-box
    assertions stay isolated."""
    rec = flight.default_flight_recorder()
    prev_dir = rec.dump_dir
    yield
    rec.dump_dir = prev_dir
    rec.clear()


def _build(seed=7, fault_policy=True, hidden=13):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2)))
    if fault_policy:
        b = b.fault_policy(True)
    conf = (b.list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(batch, N_IN)).astype(np.float32),
                    np.eye(N_OUT, dtype=np.float32)[
                        rng.integers(0, N_OUT, batch)])
            for _ in range(n)]


def _flat_params(model):
    return np.concatenate([np.asarray(v).ravel()
                           for d in model.params_ for v in d.values()])


def _flat_opt(model):
    return np.concatenate([np.asarray(s).ravel()
                           for d in model.opt_state_
                           for v in d.values() for s in v.values()])


class TestZero1Reshard:
    def _trained(self):
        m = _build()
        for ds in _batches(3):
            m.fit(ds)
        return m

    def test_roundtrip_8_2_8_bit_exact_no_host_bytes(self):
        """The acceptance round trip: (8, chunk8) → (2, chunk2) → back,
        bit-exact for every Adam slot, with zero bytes staged through
        host (transfer-size accounting) — and through a layout whose
        padding is NONZERO so the odd-count discipline is exercised
        (hidden=11 → 102 trainable floats: pads to 104 on 8 shards,
        exactly 102 on 2)."""
        m = _build(hidden=11)
        for ds in _batches(3):
            m.fit(ds)
        mesh8 = TrainingMesh(data=8)
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        l8, l2 = build_layout(m, 8), build_layout(m, 2)
        assert l8.n_padding() > 0, "pick a hidden size with odd totals"
        z8 = shard_model_opt_state(m, l8, mesh=mesh8.mesh)

        z2, st_down = reshard.reshard_zero1(z8, l8, l2, mesh2)
        z8b, st_up = reshard.reshard_zero1(z2, l2, l8, mesh8)
        assert st_down.host_bytes == 0 and st_up.host_bytes == 0
        assert st_down.device_bytes > 0
        for grp8, a, b in zip(l8.groups, z8, z8b):
            assert sorted(a) == sorted(b)
            for k in a:
                assert a[k].shape == (8, grp8.chunk)
                np.testing.assert_array_equal(np.asarray(a[k]),
                                              np.asarray(b[k]))
        # target geometry follows the M-padding discipline exactly
        for grp2, slots in zip(l2.groups, z2):
            for k in slots:
                assert slots[k].shape == (2, grp2.chunk)
                assert "data" in str(slots[k].sharding.spec)

    def test_reshard_equals_unsharded_resume(self):
        """The tentpole numerics contract: resharding the LIVE flat
        shards N→M lands bit-identically on what a canonical (unsharded)
        checkpoint resume would shard onto the M mesh."""
        m = self._trained()
        mesh8 = TrainingMesh(data=8)
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        l8, l2 = build_layout(m, 8), build_layout(m, 2)
        z8 = shard_model_opt_state(m, l8, mesh=mesh8.mesh)
        z2_direct, st = reshard.reshard_zero1(z8, l8, l2, mesh2)
        assert st.host_bytes == 0
        # the unsharded path: canonical per-layer slots → M shards
        z2_canonical = shard_model_opt_state(m, l2, mesh=mesh2.mesh)
        for a, b in zip(z2_direct, z2_canonical):
            for k in a:
                np.testing.assert_array_equal(np.asarray(a[k]),
                                              np.asarray(b[k]))

    def test_canonical_equivalence_through_m(self):
        m = self._trained()
        mesh8 = TrainingMesh(data=8)
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        l8, l2 = build_layout(m, 8), build_layout(m, 2)
        z8 = shard_model_opt_state(m, l8, mesh=mesh8.mesh)
        z2, _ = reshard.reshard_zero1(z8, l8, l2, mesh2)
        merged = l2.unshard_opt_state(z2, m.opt_state_)
        for i, layer in enumerate(m.opt_state_):
            for k, slots in layer.items():
                for s in slots:
                    np.testing.assert_array_equal(
                        np.asarray(merged[i][k][s]), np.asarray(slots[s]))

    def test_incompatible_layouts_raise(self):
        m = self._trained()
        other = _build(hidden=17)
        with pytest.raises(ValueError, match="same network"):
            reshard.check_layouts_compatible(build_layout(m, 8),
                                             build_layout(other, 2))

    def test_host_route_resplit(self):
        """A host-side (numpy) flat-shard source — what elastic recovery
        sees right after a checkpoint restore — re-splits through the
        host route with the bytes accounted, same bits."""
        m = self._trained()
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        l8, l2 = build_layout(m, 8), build_layout(m, 2)
        z8_host = [{k: np.asarray(v) for k, v in slots.items()}
                   for slots in shard_model_opt_state(m, l8)]
        z2, st = reshard.reshard_zero1(z8_host, l8, l2, mesh2)
        assert st.host_bytes > 0 and st.device_bytes == 0
        z2_ref, _ = reshard.reshard_zero1(
            shard_model_opt_state(m, l8, mesh=TrainingMesh(data=8).mesh),
            l8, l2, mesh2)
        for a, b in zip(z2, z2_ref):
            for k in a:
                np.testing.assert_array_equal(np.asarray(a[k]),
                                              np.asarray(b[k]))


class TestPlanExecute:
    def test_plan_routes_and_summary(self):
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        tree = {"live": jax.numpy.ones((4, 4)),
                "host": np.ones((8,), np.float32),
                "skip": None}
        plan = reshard.plan_replicated(tree, mesh2, n_from=8)
        s = plan.summary()
        assert s["n_from"] == 8 and s["n_to"] == 2
        assert s["routes"][reshard.ROUTE_DEVICE] == 1
        assert s["routes"][reshard.ROUTE_HOST] == 1
        out, st = plan.execute(tree)
        assert st.host_bytes == 32 and st.device_bytes == 64
        assert out["skip"] is None
        for k in ("live", "host"):
            assert isinstance(out[k], jax.Array)
            np.testing.assert_array_equal(np.asarray(out[k]),
                                          np.asarray(tree[k]))

    def test_execute_rejects_changed_structure(self):
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        plan = reshard.plan_replicated({"a": np.ones(3)}, mesh2)
        with pytest.raises(ValueError, match="structure changed"):
            plan.execute({"a": np.ones(3), "b": np.ones(3)})

    def test_gather_to_host_accounts_everything(self):
        tree = {"a": jax.numpy.ones((16,), jax.numpy.float32)}
        out, st = reshard.gather_to_host(tree)
        assert isinstance(out["a"], np.ndarray)
        assert st.host_bytes == 64 and st.device_bytes == 0


class TestCheckpointPortability:
    def test_meta_carries_rng_fault_state_topology(self, tmp_path):
        m = _build()
        for ds in _batches(2):
            m.fit(ds)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(m, path)
        meta = ModelSerializer.checkpoint_meta(path)
        # topology is the mesh the fit ACTUALLY used (read off the
        # params' sharding), not the host's device count: a plain
        # single-device fit records 1 even on this 8-device host
        assert meta["topology"]["n_devices"] == 1
        assert meta["topology"]["backend"] == jax.default_backend()
        assert meta["rng"] == [int(v) for v in np.asarray(m._rng).ravel()]
        assert meta["fault_state"]["good_count"] == 2

        restored = ModelSerializer.restore_multi_layer_network(path)
        np.testing.assert_array_equal(np.asarray(restored._rng),
                                      np.asarray(m._rng))
        for k in m.fault_state_:
            assert np.asarray(restored.fault_state_[k]) == np.asarray(
                m.fault_state_[k])

        # ... and a ParallelWrapper fit records the wrapper's mesh size,
        # not len(jax.devices()) — the --workers 2 case the provenance
        # exists for
        pw = ParallelWrapper(
            m, mesh=TrainingMesh(data=2, devices=jax.devices()[:2]))
        pw.fit(ExistingDataSetIterator(_batches(1)), epochs=1)
        path2 = str(tmp_path / "ckpt2.zip")
        ModelSerializer.write_model(m, path2)
        assert (ModelSerializer.checkpoint_meta(path2)
                ["topology"]["n_devices"] == 2)

    def test_legacy_checkpoint_without_new_keys_loads(self, tmp_path):
        """Pre-PR-8 checkpoints (no rng/fault_state/topology in meta)
        keep the old semantics: fresh chain, fault state rebuilt from
        the iteration counter at fit entry."""
        from deeplearning4j_tpu.train.model_serializer import META_ENTRY

        m = _build()
        for ds in _batches(2):
            m.fit(ds)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(m, path)
        legacy = str(tmp_path / "legacy.zip")
        with zipfile.ZipFile(path) as zin, \
                zipfile.ZipFile(legacy, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == META_ENTRY:
                    meta = json.loads(data.decode())
                    for k in ("rng", "fault_state", "topology"):
                        meta.pop(k, None)
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        restored = ModelSerializer.restore_multi_layer_network(legacy)
        assert restored.iteration == 2
        assert restored.fault_state_ is None
        np.testing.assert_array_equal(
            np.asarray(restored._rng),
            np.asarray(jax.random.PRNGKey(7)))

    def test_loss_scale_round_trips(self, tmp_path):
        m = _build()
        policy = faults.FaultPolicy(loss_scaling=True,
                                    init_loss_scale=1024.0)
        m.fault_state_ = faults.init_fault_state(policy, scaling=True,
                                                 start_step=5)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(m, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        assert float(restored.fault_state_["loss_scale"]) == 1024.0
        assert int(restored.fault_state_["scale_good"]) == 0
        assert int(restored.fault_state_["good_count"]) == 5


class TestMeshFailureTaxonomy:
    def test_is_mesh_failure_classification(self):
        assert is_mesh_failure(MeshFailureError("x"))
        assert is_mesh_failure(InjectedHostDropout("x"))
        assert is_mesh_failure(RuntimeError("DEADLINE: heartbeat timeout"
                                            .lower()))
        assert is_mesh_failure(RuntimeError("coordination service error"))
        assert not is_mesh_failure(ValueError("shape mismatch (4,) (8,)"))
        assert not is_mesh_failure(RuntimeError("NaN loss"))

    def test_probe_devices_all_healthy(self):
        devs = jax.devices()
        assert faults.probe_devices(devs) == list(devs)

    def test_injection_is_one_shot(self):
        with host_dropout_injection(at_iteration=3, survivors=4):
            faults.check_host_dropout(2)  # below threshold: no fire
            with pytest.raises(InjectedHostDropout) as ei:
                faults.check_host_dropout(3)
            assert len(ei.value.survivors) == 4
            faults.check_host_dropout(5)  # already fired: silent
        faults.check_host_dropout(99)  # disarmed outside the context

    def test_mesh_shrink_rejects_model_axes(self):
        mesh = TrainingMesh(data=4, model=2)
        with pytest.raises(ValueError, match="data-parallel only"):
            mesh.shrink(jax.devices()[:2])


class TestElasticDrill:
    def _comparator(self, batches, split, n_to, sharded=False):
        """Uninterrupted fit over the SAME mesh sequence the recovery
        produces (8-mesh before the checkpoint, survivor mesh after) —
        the bit-exact oracle. Cross-device-count reduction order is the
        one documented tolerance, so a pure-8 uninterrupted run is only
        allclose-comparable, and that is asserted separately."""
        comp = _build()
        pw8 = ParallelWrapper(comp, mesh=TrainingMesh(data=8),
                              sharded_update=sharded)
        pw8.fit(ExistingDataSetIterator(batches[:split]), epochs=1)
        comp.epoch = 0
        pw_m = ParallelWrapper(
            comp, mesh=TrainingMesh(data=n_to,
                                    devices=jax.devices()[:n_to]),
            sharded_update=sharded)
        pw_m.fit(ExistingDataSetIterator(batches[split:]), epochs=1)
        return comp

    def test_host_dropout_recovery_bit_identical(self, tmp_path):
        """THE acceptance drill: injected host dropout mid-fit on the
        8-device mesh → survivors re-form a 4-device mesh → resume from
        latest_valid_checkpoint → final params AND Adam slots
        bit-identical to an uninterrupted run over the same batch
        schedule (and same mesh sequence), with the full
        mesh_shrink → reshard_start → reshard_done → elastic_resume
        sequence in the flight-recorder dump."""
        batches = _batches(12)
        rec = flight.default_flight_recorder()
        rec.clear()
        rec.dump_dir = str(tmp_path)

        drill = _build()
        driver = ElasticFitDriver(drill, str(tmp_path / "ckpts"),
                                  max_retries=2)
        with host_dropout_injection(at_iteration=6, survivors=4):
            driver.fit(batches, epochs=1)
        drill = driver.model
        assert driver.recoveries == 1
        assert drill.iteration == 12 and drill.epoch == 1

        comp = self._comparator(batches, split=6, n_to=4)
        np.testing.assert_array_equal(_flat_params(drill),
                                      _flat_params(comp))
        np.testing.assert_array_equal(_flat_opt(drill), _flat_opt(comp))
        np.testing.assert_array_equal(np.asarray(drill._rng),
                                      np.asarray(comp._rng))
        # documented tolerance vs the pure-8 uninterrupted run:
        # reduction order across device counts, nothing else
        pure8 = _build()
        ParallelWrapper(pure8, mesh=TrainingMesh(data=8)).fit(
            ExistingDataSetIterator(batches), epochs=1)
        np.testing.assert_allclose(_flat_params(drill),
                                   _flat_params(pure8), atol=5e-6)

        # the black box shows the recovery timeline, in order
        path = rec.dump(reason="drill")
        with open(path) as f:
            body = json.load(f)
        kinds = [e["kind"] for e in body["events"]]
        want = ["mesh_shrink", "reshard_start", "reshard_done",
                "elastic_resume"]
        idx = [kinds.index(k) for k in want]
        assert idx == sorted(idx), f"bad event order: {kinds}"
        done = body["events"][kinds.index("reshard_done")]
        assert done["n_from"] == 8 and done["n_to"] == 4
        assert done["wall_ms"] >= 0 and done["host_bytes"] == 0
        # cli flight-dump renders the sequence
        text = flight.format_dump(body)
        for k in want:
            assert k in text

    def test_drill_zero1_sharded_update(self, tmp_path):
        """Same drill under the ZeRO-1 sharded weight update: recovery
        re-shards the checkpointed canonical slots onto the survivor
        mesh and stays bit-identical to the same-mesh-sequence run."""
        batches = _batches(8)
        drill = _build()
        driver = ElasticFitDriver(drill, str(tmp_path / "ckpts"),
                                  max_retries=1, sharded_update=True)
        with host_dropout_injection(at_iteration=4, survivors=2):
            driver.fit(batches, epochs=1)
        drill = driver.model
        comp = self._comparator(batches, split=4, n_to=2, sharded=True)
        np.testing.assert_array_equal(_flat_params(drill),
                                      _flat_params(comp))
        np.testing.assert_array_equal(_flat_opt(drill), _flat_opt(comp))

    def test_giveup_typed_error_and_event(self, tmp_path):
        batches = _batches(6)
        rec = flight.default_flight_recorder()
        rec.clear()
        drill = _build()
        driver = ElasticFitDriver(drill, str(tmp_path / "ckpts"),
                                  max_retries=0)
        with host_dropout_injection(at_iteration=3, survivors=4):
            with pytest.raises(ElasticRecoveryExhaustedError,
                               match="intact"):
                driver.fit(batches, epochs=1)
        kinds = [e["kind"] for e in rec.events()]
        assert "elastic_giveup" in kinds
        assert "elastic_resume" not in kinds
        # state is NOT lost: the newest checkpoint is on disk and valid
        assert faults.latest_valid_checkpoint(str(tmp_path / "ckpts"))

    def test_foreign_checkpoint_typed_giveup(self, tmp_path):
        """A stale/foreign checkpoint_dir is never silently adopted:
        recovery validates the restored iteration against this fit's
        range — a foreign newest checkpoint (here iteration 500) would
        otherwise declare the fit complete with someone else's model."""
        ckdir = str(tmp_path / "ckpts")
        foreign = _build(seed=11)
        foreign.fit(_batches(1)[0])
        foreign.iteration = 500
        faults.save_checkpoint(foreign, ckdir)
        drill = _build()
        # cadence so high this run writes no checkpoint of its own
        driver = ElasticFitDriver(drill, ckdir, max_retries=2,
                                  checkpoint_every_n_iterations=10**6)
        with host_dropout_injection(at_iteration=2, survivors=4):
            with pytest.raises(ElasticRecoveryExhaustedError,
                               match="different run"):
                driver.fit(_batches(6), epochs=1)

    def test_midrun_checkpoints_carry_logical_epoch(self, tmp_path):
        """The flattened schedule runs as one ParallelWrapper epoch, but
        every checkpoint must carry the epoch a plain epochs-loop fit
        would have recorded at that iteration — that is what a crash +
        --resume restores, and what save_every_n_epochs listeners key
        on."""
        ckdir = str(tmp_path / "ckpts")
        drill = _build()
        driver = ElasticFitDriver(drill, ckdir, keep_last=100)
        driver.fit(_batches(4), epochs=3)
        assert driver.model.epoch == 3
        metas = sorted(
            (ModelSerializer.checkpoint_meta(os.path.join(ckdir, f))
             for f in os.listdir(ckdir) if f.endswith(".zip")),
            key=lambda m: m["iteration"])
        assert [m["iteration"] for m in metas] == list(range(1, 13))
        # iterations 1-4 are epoch 0, 5-8 epoch 1, 9-12 epoch 2 (the
        # bump to 3 lands after the last iteration's checkpoint)
        assert [m["epoch"] for m in metas] == [(i - 1) // 4
                                               for i in range(1, 13)]

    def test_min_devices_floor(self, tmp_path):
        batches = _batches(6)
        drill = _build()
        driver = ElasticFitDriver(drill, str(tmp_path / "ckpts"),
                                  max_retries=3, min_devices=4)
        with host_dropout_injection(at_iteration=3, survivors=2):
            with pytest.raises(ElasticRecoveryExhaustedError):
                driver.fit(batches, epochs=1)

    def test_non_mesh_failure_propagates(self, tmp_path):
        """A programming error (bad shapes) must never be 'recovered' by
        silently shrinking the mesh and replaying the checkpoint."""
        drill = _build()
        driver = ElasticFitDriver(drill, str(tmp_path / "ckpts"))
        bad = _batches(3)
        bad[1] = DataSet(bad[1].features[:, :2], bad[1].labels)  # shape bug
        with pytest.raises(Exception) as ei:
            driver.fit(bad, epochs=1)
        assert not isinstance(ei.value, ElasticRecoveryExhaustedError)
        assert driver.recoveries == 0


class TestServingFallback:
    def _ckpt_dir(self, tmp_path):
        m = _build(fault_policy=False)
        for ds in _batches(2):
            m.fit(ds)
        d = str(tmp_path / "ckpts")
        p1 = faults.save_checkpoint(m, d, stem="ckpt_a")
        m.fit(_batches(1)[0])
        p2 = faults.save_checkpoint(m, d, stem="ckpt_b")
        return d, p1, p2, m

    def test_explicit_corrupt_path_falls_back(self, tmp_path):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        d, p1, p2, m = self._ckpt_dir(tmp_path)
        faults.truncate_file(p2)
        rec = flight.default_flight_recorder()
        rec.clear()
        with pytest.warns(UserWarning, match="newest valid sibling"):
            eng = InferenceEngine.from_checkpoint(p2)
        assert str(eng.describe()["source"]) == p1
        kinds = [e["kind"] for e in rec.events()]
        assert "checkpoint_fallback" in kinds
        x = np.zeros((2, N_IN), np.float32)
        assert eng.infer(x).shape[1] == N_OUT

    def test_explicit_corrupt_path_no_sibling_raises(self, tmp_path):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        m = _build(fault_policy=False)
        p = str(tmp_path / "only.zip")
        ModelSerializer.write_model(m, p)
        faults.truncate_file(p)
        with pytest.raises(ValueError, match="no valid sibling"):
            InferenceEngine.from_checkpoint(p)

    def test_from_checkpoint_records_reshard_provenance(self, tmp_path):
        """Train-on-8/serve-on-1: the checkpoint's topology provenance
        (written on the 8-device mesh) lands in the reshard events."""
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        m = _build(fault_policy=False)
        ParallelWrapper(m, mesh=TrainingMesh(data=8)).fit(
            ExistingDataSetIterator(_batches(2)), epochs=1)
        d = str(tmp_path / "ckpts")
        faults.save_checkpoint(m, d, stem="ckpt_a")
        rec = flight.default_flight_recorder()
        rec.clear()
        eng = InferenceEngine.from_checkpoint(d)
        evs = {e["kind"]: e for e in rec.events()}
        assert "reshard_start" in evs and "reshard_done" in evs
        assert evs["reshard_done"]["n_from"] == 8
        assert evs["reshard_done"]["n_to"] == 1
        x = np.zeros((2, N_IN), np.float32)
        np.testing.assert_allclose(eng.infer(x), m.output(x), atol=1e-6)

    def test_serve_on_submesh(self, tmp_path):
        """Any-topology serving: an 8-device training checkpoint serves
        on a 2-device mesh, outputs equal to the source model."""
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        d, p1, p2, m = self._ckpt_dir(tmp_path)
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        eng = InferenceEngine.from_checkpoint(d, mesh=mesh2)
        assert eng.reshard_stats is not None
        assert eng.reshard_stats.leaves > 0
        x = np.zeros((4, N_IN), np.float32)
        np.testing.assert_allclose(eng.infer(x), m.output(x), atol=1e-6)


class TestTuneMigration:
    def test_migrate_trial_between_pools(self, tmp_path):
        from deeplearning4j_tpu.tune import migrate_trial
        from deeplearning4j_tpu.tune.store import TrialStore

        store = TrialStore(str(tmp_path / "study"))
        m = _build()
        for ds in _batches(3):
            m.fit(ds)
        store.save_trial_checkpoint(m, "t0001", rung_index=0, keep_last=2)
        rec = flight.default_flight_recorder()
        rec.clear()

        target = jax.devices()[3]
        moved, ckpt = migrate_trial(store, "t0001", target_device=target)
        assert moved.iteration == 3
        np.testing.assert_array_equal(_flat_params(moved), _flat_params(m))
        np.testing.assert_array_equal(_flat_opt(moved), _flat_opt(m))
        np.testing.assert_array_equal(np.asarray(moved._rng),
                                      np.asarray(m._rng))
        leaf = moved.params_[0]["W"]
        assert list(leaf.devices()) == [target]
        kinds = [e["kind"] for e in rec.events()]
        assert "reshard_done" in kinds

        # ...and onto a data-parallel pool (mesh target)
        mesh2 = TrainingMesh(data=2, devices=jax.devices()[:2])
        moved2, _ = migrate_trial(store, "t0001", target_mesh=mesh2)
        np.testing.assert_array_equal(_flat_params(moved2),
                                      _flat_params(m))

    def test_migrate_unknown_trial_raises(self, tmp_path):
        from deeplearning4j_tpu.tune import migrate_trial
        from deeplearning4j_tpu.tune.store import TrialStore

        store = TrialStore(str(tmp_path / "study"))
        with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
            migrate_trial(store, "nope", target_device=jax.devices()[0])

    def test_migrate_requires_exactly_one_target(self, tmp_path):
        from deeplearning4j_tpu.tune import migrate_trial
        from deeplearning4j_tpu.tune.store import TrialStore

        store = TrialStore(str(tmp_path / "study"))
        with pytest.raises(ValueError, match="exactly one"):
            migrate_trial(store, "t0", target_device=None, target_mesh=None)


class TestDriverConfig:
    def test_driver_requires_checkpoint_dir(self):
        with pytest.raises(ValueError, match="checkpoint_dir"):
            ElasticFitDriver(_build(), "")

    def test_driver_empty_schedule_noop(self, tmp_path):
        m = _build()
        driver = ElasticFitDriver(m, str(tmp_path / "ckpts"))
        assert driver.fit([], epochs=1) is m
        assert m.iteration == 0
