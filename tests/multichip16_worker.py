"""16-virtual-device full-mesh worker (VERDICT r3 item 8): runs in its
own process so the device count can exceed the suite's 8-device default.

Exercises, with single-device parity checks in-process:
- TransformerLM on the full 4-axis mesh data=2 x model=2 x pipe=2 x seq=2
  (16 devices), n_micro=8;
- MoE LM with EP over data=2 x model=2 x expert=4 (GShard composition —
  PP+MoE is rejected by design).

Writes <outdir>/ok on success (parent asserts existence).
"""

import os
import sys

outdir = sys.argv[1]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.models.transformer_lm import TransformerLM  # noqa: E402
from deeplearning4j_tpu.parallel.mesh import TrainingMesh  # noqa: E402
from deeplearning4j_tpu.parallel.transformer import (  # noqa: E402
    DistributedLMTrainer,
)

assert len(jax.devices()) == 16, jax.devices()

V, T, B = 31, 16, 8
rng = np.random.default_rng(0)
ids = rng.integers(0, V, (B, T)).astype(np.int32)
tgt = np.roll(ids, -1, axis=1).astype(np.int32)
tgt[:, -1] = -1

# --- dense LM on the full 4-axis mesh --------------------------------------
m_ref = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=4,
                      max_length=T).init()
ref_losses = [m_ref.fit_batch(ids, tgt) for _ in range(3)]

m = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=4,
                  max_length=T).init()
mesh = TrainingMesh(data=2, model=2, pipe=2, seq=2)
tr = DistributedLMTrainer(m, mesh, n_micro=8).place()
assert abs(tr.bubble_fraction - 1 / 9) < 1e-9, tr.bubble_fraction
losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=1e-4)
print("dense 2x2x2x2 parity ok:", losses, flush=True)

# --- MoE LM: EP composed with dp+tp ----------------------------------------
moe_ref = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=2,
                        max_length=T, n_experts=4, top_k=2).init()
moe_ref_losses = [moe_ref.fit_batch(ids, tgt) for _ in range(3)]

moe = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=2,
                    max_length=T, n_experts=4, top_k=2).init()
moe_mesh = TrainingMesh(data=2, model=2, expert=4)
moe_tr = DistributedLMTrainer(moe, moe_mesh).place()
moe_losses = [moe_tr.fit_batch(ids, tgt) for _ in range(3)]
np.testing.assert_allclose(moe_losses, moe_ref_losses, rtol=2e-3, atol=1e-4)
print("moe dp2xtp2xep4 parity ok:", moe_losses, flush=True)

with open(os.path.join(outdir, "ok"), "w") as f:
    f.write("ok")
