"""MultiLayerNetwork behavioral tests.

Modeled on reference ``nn/multilayer/MultiLayerTest.java`` (1,289 LoC) and
config serde tests (SURVEY.md §4.2-4.3).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, MultiLayerConfiguration, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.listeners import (
    CollectScoresIterationListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.train.model_serializer import ModelSerializer
from deeplearning4j_tpu.updaters import Adam, Sgd


def small_classification_data(n=128, n_in=4, n_classes=3, seed=0):
    rng = np.random.default_rng(seed)
    # separable blobs
    centers = rng.standard_normal((n_classes, n_in)) * 3
    cls = rng.integers(0, n_classes, n)
    x = centers[cls] + rng.standard_normal((n, n_in)) * 0.5
    y = np.eye(n_classes, dtype=np.float32)[cls]
    return DataSet(x.astype(np.float32), y)


def mlp_conf(n_in=4, n_classes=3, updater=None):
    return (
        NeuralNetConfiguration.builder()
        .seed(42)
        .updater(updater or Adam(0.01))
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(n_in))
        .build()
    )


class TestBuild:
    def test_shape_inference(self):
        conf = mlp_conf()
        assert conf.layers[0].n_in == 4
        assert conf.layers[1].n_in == 16

    def test_global_defaults_propagate(self):
        conf = (
            NeuralNetConfiguration.builder()
            .updater(Sgd(0.5))
            .weight_init("relu")
            .l2(1e-3)
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3))
            .build()
        )
        l0 = conf.layers[0]
        assert isinstance(l0.updater, Sgd)
        assert l0.weight_init == "relu"
        assert l0.regularization.l2 == pytest.approx(1e-3)
        assert l0.activation == "tanh"  # layer override wins

    def test_cnn_preprocessor_auto_insert(self):
        conf = (
            NeuralNetConfiguration.builder()
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3, activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2))
            .layer(DenseLayer(n_out=10, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build()
        )
        # CNN→FF preprocessor before the dense layer
        assert 2 in conf.preprocessors
        types = conf.layer_types()
        # conv: 12-3+1=10, pool → 5; flatten 5*5*4=100
        assert conf.layers[2].n_in == 100

    def test_json_roundtrip(self):
        conf = mlp_conf()
        js = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(js)
        assert conf == conf2
        net = MultiLayerNetwork(conf2).init()
        assert net.num_params() == (4 * 16 + 16) + (16 * 3 + 3)


class TestTraining:
    def test_mlp_learns_blobs(self):
        ds = small_classification_data()
        net = MultiLayerNetwork(mlp_conf()).init()
        s0 = None
        for epoch in range(30):
            net.fit(ds, batch_size=32)
            if s0 is None:
                s0 = net.score()
        ev = net.evaluate(ds)
        assert ev.accuracy() > 0.9, ev.stats()
        assert net.score() < s0

    def test_score_decreases_sgd(self):
        ds = small_classification_data()
        net = MultiLayerNetwork(mlp_conf(updater=Sgd(0.1))).init()
        net.fit(ds, batch_size=128)
        first = net.score()
        for _ in range(20):
            net.fit(ds, batch_size=128)
        assert net.score() < first

    def test_listeners_called(self):
        ds = small_classification_data(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        collect = CollectScoresIterationListener(frequency=1)
        printed = []
        net.set_listeners(collect, ScoreIterationListener(1, printer=printed.append))
        net.fit(ds, batch_size=32)  # 2 iterations
        assert len(collect.scores) == 2
        assert len(printed) == 2

    def test_fit_ndarray_api(self):
        ds = small_classification_data(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        net.fit(ds.features, ds.labels, epochs=2, batch_size=32)
        assert net.iteration == 4

    def test_param_and_gradient_listener(self, tmp_path):
        """reference ParamAndGradientIterationListener: tab-delimited
        per-parameter stats of params AND gradients (gradients via the
        introspection hook), header + one row per reporting iteration."""
        from deeplearning4j_tpu.train.listeners import (
            ComposableIterationListener,
            ParamAndGradientIterationListener,
        )

        ds = small_classification_data(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        path = str(tmp_path / "pg.tsv")
        pg = ParamAndGradientIterationListener(
            iterations=1, output_to_console=False, file=path)
        collect = CollectScoresIterationListener(frequency=1)
        net.set_listeners(ComposableIterationListener(pg, collect))
        net.fit(ds, batch_size=32)  # 2 iterations
        lines = open(path).read().strip().split("\n")
        assert len(lines) == 3  # header + 2 iterations
        header, rows = lines[0].split("\t"), lines[1:]
        assert header[0] == "iteration"
        assert any(c.startswith("p_") and c.endswith("_mean")
                   for c in header)
        # gradient columns exist => introspection hook delivered through
        # the composable wrapper
        assert any(c.startswith("g_") for c in header)
        for r in rows:
            vals = r.split("\t")
            assert len(vals) == len(header)
            assert all(np.isfinite(float(v)) for v in vals[1:])
        # the composed child listener was also driven
        assert len(collect.scores) == 2

    def test_evaluative_listener_model_saving_callback(self, tmp_path):
        """reference EvaluationCallback SPI + ModelSavingCallback: the
        callback fires per evaluation and checkpoints with %d replaced
        by the invocation count."""
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.train.listeners import (
            EvaluativeListener,
            model_saving_callback,
        )
        from deeplearning4j_tpu.train.model_serializer import (
            ModelSerializer,
        )

        ds = small_classification_data(n=32)
        net = MultiLayerNetwork(mlp_conf()).init()
        it = ListDataSetIterator(ds, batch_size=32)
        net.set_listeners(EvaluativeListener(
            it, frequency=1, invocation="epoch_end",
            printer=lambda s: None,
            callback=model_saving_callback(str(tmp_path), "model-%d.zip")))
        net.fit(ds, batch_size=16, epochs=2)
        import os

        saved = sorted(os.listdir(tmp_path))
        assert saved == ["model-1.zip", "model-2.zip"], saved
        back = ModelSerializer.restore_multi_layer_network(
            str(tmp_path / "model-2.zip"))
        np.testing.assert_allclose(back.output(ds.features),
                                   net.output(ds.features), atol=1e-6)

    def test_output_shape_and_softmax(self):
        ds = small_classification_data(n=16)
        net = MultiLayerNetwork(mlp_conf()).init()
        out = net.output(ds.features)
        assert out.shape == (16, 3)
        np.testing.assert_allclose(out.sum(axis=1), np.ones(16), rtol=1e-4)

    def test_l2_regularization_shrinks_weights(self):
        ds = small_classification_data()
        conf_reg = (
            NeuralNetConfiguration.builder()
            .seed(42).updater(Sgd(0.1)).l2(0.5)
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        net_reg = MultiLayerNetwork(conf_reg).init()
        net_plain = MultiLayerNetwork(mlp_conf(updater=Sgd(0.1))).init()
        for _ in range(10):
            net_reg.fit(ds, batch_size=128)
            net_plain.fit(ds, batch_size=128)
        w_reg = np.linalg.norm(np.asarray(net_reg.params_[0]["W"]))
        w_plain = np.linalg.norm(np.asarray(net_plain.params_[0]["W"]))
        assert w_reg < w_plain

    def test_frozen_layer_params_fixed(self):
        from deeplearning4j_tpu.nn.conf.layers import FrozenLayer

        ds = small_classification_data(n=64)
        conf = (
            NeuralNetConfiguration.builder()
            .seed(42).updater(Sgd(0.5))
            .list()
            .layer(FrozenLayer(layer=DenseLayer(n_out=16, activation="relu")))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        w_before = np.asarray(net.params_[0]["W"]).copy()
        out_w_before = np.asarray(net.params_[1]["W"]).copy()
        net.fit(ds, batch_size=64)
        np.testing.assert_array_equal(np.asarray(net.params_[0]["W"]), w_before)
        assert not np.array_equal(np.asarray(net.params_[1]["W"]), out_w_before)


class TestCnn:
    def test_small_cnn_trains(self):
        rng = np.random.default_rng(0)
        n = 64
        x = rng.standard_normal((n, 8, 8, 1)).astype(np.float32)
        # class = whether center pixel is positive
        cls = (x[:, 4, 4, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        conf = (
            NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(0.01))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3, activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y)
        for _ in range(30):
            net.fit(ds, batch_size=64)
        assert net.evaluate(ds).accuracy() > 0.85

    def test_batchnorm_state_updates(self):
        rng = np.random.default_rng(0)
        x = (rng.standard_normal((32, 6)) * 5 + 2).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        conf = (
            NeuralNetConfiguration.builder()
            .updater(Sgd(0.01))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(6))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        mean_before = np.asarray(net.state_[1]["mean"]).copy()
        net.fit(DataSet(x, y), batch_size=32)
        mean_after = np.asarray(net.state_[1]["mean"])
        assert not np.allclose(mean_before, mean_after)


class TestRnn:
    def _seq_data(self, n=32, t=10, d=3, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((n, t, d)).astype(np.float32)
        cls = (x.mean(axis=(1, 2)) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        return DataSet(x, y)

    def test_lstm_classifier_trains(self):
        ds = self._seq_data()
        conf = (
            NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(0.02))
            .list()
            .layer(LSTM(n_out=8))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.recurrent(3, 10))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        for _ in range(40):
            net.fit(ds, batch_size=32)
        assert net.evaluate(ds).accuracy() > 0.85

    def test_rnn_output_layer_per_timestep(self):
        rng = np.random.default_rng(0)
        n, t, d = 16, 6, 4
        x = rng.standard_normal((n, t, d)).astype(np.float32)
        cls = (x.sum(axis=2) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]  # (n, t, 2)
        conf = (
            NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(0.02))
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(d, t))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y)
        for _ in range(10):
            net.fit(ds, batch_size=16)
        out = net.output(x)
        assert out.shape == (n, t, 2)

    def test_masked_sequences(self):
        rng = np.random.default_rng(0)
        n, t, d = 16, 8, 3
        x = rng.standard_normal((n, t, d)).astype(np.float32)
        lengths = rng.integers(2, t + 1, n)
        mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
        cls = np.array([
            (x[i, : lengths[i]].mean() > 0) for i in range(n)
        ]).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        conf = (
            NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(0.02))
            .list()
            .layer(LSTM(n_out=8))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.recurrent(d, t))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y, features_mask=mask)
        net.fit(ds, batch_size=16)  # must run without error
        out = net.output(x, mask=mask)
        assert out.shape == (n, 2)

    def test_rnn_time_step_matches_full_forward(self):
        ds = self._seq_data(n=4, t=6)
        conf = (
            NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(0.02))
            .list()
            .layer(LSTM(n_out=5))
            .layer(RnnOutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.recurrent(3, 6))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        full = net.output(ds.features)
        net.rnn_clear_previous_state()
        stepped = []
        for t in range(6):
            stepped.append(net.rnn_time_step(ds.features[:, t, :]))
        stepped = np.stack(stepped, axis=1)
        np.testing.assert_allclose(full, stepped, atol=1e-5)

    def test_tbptt_runs(self):
        ds = self._seq_data(n=8, t=20)
        conf = (
            NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(0.02))
            .list()
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2, activation="softmax"))
            .backprop_type("tbptt", fwd_length=5, back_length=5)
            .set_input_type(InputType.recurrent(3, 20))
            .build()
        )
        # per-timestep labels for tbptt chunking
        rng = np.random.default_rng(0)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (8, 20))]
        net = MultiLayerNetwork(conf).init()
        net.fit(DataSet(ds.features, y), batch_size=8)
        assert net.iteration == 1


class TestSerialization:
    def test_save_restore_roundtrip(self, tmp_path):
        ds = small_classification_data(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        net.fit(ds, batch_size=32)
        path = os.path.join(tmp_path, "model.zip")
        ModelSerializer.write_model(net, path)
        net2 = ModelSerializer.restore_multi_layer_network(path)
        np.testing.assert_array_equal(net.params_flat(), net2.params_flat())
        np.testing.assert_array_equal(net.opt_state_flat(), net2.opt_state_flat())
        assert net2.iteration == net.iteration
        out1 = net.output(ds.features)
        out2 = net2.output(ds.features)
        np.testing.assert_allclose(out1, out2, atol=1e-6)

    def test_resume_training_continuity(self, tmp_path):
        ds = small_classification_data(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        for _ in range(3):
            net.fit(ds, batch_size=64)
        path = os.path.join(tmp_path, "ckpt.zip")
        ModelSerializer.write_model(net, path)
        net2 = ModelSerializer.restore_multi_layer_network(path)
        net2.fit(ds, batch_size=64)  # must continue without error
        assert net2.iteration == net.iteration + 1


class TestTopNEvaluate:
    def test_top_n_accuracy_at_least_top1(self):
        """evaluate(it, top_n=3) (reference topN overload): top-3 accuracy
        is >= top-1 and uses the merged counters."""
        ds = small_classification_data()
        conf = mlp_conf()
        net = MultiLayerNetwork(conf).init()
        net.fit(ds, epochs=3, batch_size=32)
        ev1 = net.evaluate(ds)
        ev3 = net.evaluate(ds, top_n=3)
        assert ev3.top_n_total == ds.features.shape[0]
        top3 = ev3.top_n_correct / ev3.top_n_total
        assert top3 >= ev1.accuracy() - 1e-9
        assert top3 == 1.0  # 3 classes, top-3 always contains the label

    def test_top_n_with_single_sigmoid_column(self):
        """top-N over a 1-column sigmoid output ranks the two implied
        classes (review regression: argsort over one column counted only
        class-0 rows)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        rng = np.random.default_rng(0)
        labels = rng.integers(0, 2, 50)
        probs = rng.random((50, 1)).astype(np.float32)
        ev = Evaluation(top_n=2)
        ev.eval(labels.reshape(-1, 1).astype(np.float32), probs)
        assert ev.top_n_total == 50
        assert ev.top_n_correct == 50  # top-2 of 2 classes always hits

    def test_micro_macro_averaging_and_pr_curve(self):
        """reference EvaluationAveraging Micro/Macro on precision/recall/
        f1 + ROC.getPrecisionRecallCurve."""
        from deeplearning4j_tpu.evaluation import Evaluation, ROC

        labels = np.eye(3, dtype=np.float32)[[0, 0, 0, 0, 1, 1, 2, 2]]
        preds = np.eye(3, dtype=np.float32)[[0, 0, 1, 2, 1, 1, 2, 0]]
        ev = Evaluation()
        ev.eval(labels, preds)
        # micro precision == micro recall == accuracy for single-label
        assert ev.precision(averaging="micro") == pytest.approx(
            ev.accuracy())
        assert ev.recall(averaging="micro") == pytest.approx(ev.accuracy())
        assert ev.f1(averaging="micro") == pytest.approx(ev.accuracy())
        # macro differs here (class imbalance) and stays in [0, 1]
        assert 0.0 <= ev.precision(averaging="macro") <= 1.0
        assert ev.precision(averaging="macro") != pytest.approx(
            ev.precision(averaging="micro"))

        rng = np.random.default_rng(3)
        y = rng.integers(0, 2, 200)
        s = np.clip(y * 0.6 + rng.random(200) * 0.5, 0, 1)
        roc = ROC()
        roc.eval(y.reshape(-1, 1).astype(np.float32),
                 s.reshape(-1, 1).astype(np.float32))
        rec, prec = roc.get_precision_recall_curve()
        assert rec.shape == prec.shape and len(rec) > 10
        assert rec.min() >= 0 and rec.max() <= 1
        assert prec.min() >= 0 and prec.max() <= 1
        # area under the exported points == calculate_auprc
        from deeplearning4j_tpu.evaluation.roc import _auc

        assert _auc(rec, prec) == pytest.approx(roc.calculate_auprc())

    def test_macro_f1_is_mean_of_per_class_f1(self):
        """reference Evaluation.fBeta(Macro) semantics
        (eval/Evaluation.java:1193-1203): macro F1 averages per-class F1
        scores (NOT the harmonic mean of macro-P and macro-R), and the
        2-class case returns the binary F1 of class 1."""
        from deeplearning4j_tpu.evaluation import Evaluation

        # imbalanced 3-class confusion where the two definitions diverge:
        # rows actual [[8,1,1],[3,1,1],[1,0,4]] -> mean-of-F1 0.5801,
        # harmonic-of-macro-P/R 0.6055
        actual_cls = [0] * 10 + [1] * 5 + [2] * 5
        pred_cls = ([0] * 8 + [1, 2] + [0, 0, 0, 1, 2] + [0, 2, 2, 2, 2])
        labels = np.eye(3, dtype=np.float32)[actual_cls]
        preds = np.eye(3, dtype=np.float32)[pred_cls]
        ev = Evaluation()
        ev.eval(labels, preds)
        expected = np.mean([ev.f1(i) for i in range(3)])
        assert ev.f1(averaging="macro") == pytest.approx(expected)
        harmonic = 2 * ev.precision() * ev.recall() / (
            ev.precision() + ev.recall())
        assert ev.f1(averaging="macro") != pytest.approx(harmonic)

        # 2-class special case: binary F1 of class 1
        labels2 = np.eye(2, dtype=np.float32)[[0, 0, 0, 1, 1, 0]]
        preds2 = np.eye(2, dtype=np.float32)[[0, 1, 0, 1, 0, 0]]
        ev2 = Evaluation()
        ev2.eval(labels2, preds2)
        assert ev2.f1(averaging="macro") == pytest.approx(ev2.f1(1))

    def test_eval_meta_mismatch_leaves_state_unchanged(self):
        """a failed record_meta_data eval() must not partially mutate the
        Evaluation (confusion counted, predictions dropped)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        labels = np.eye(2, dtype=np.float32)[[0, 1]]
        preds = np.eye(2, dtype=np.float32)[[0, 1]]
        ev = Evaluation()
        with pytest.raises(ValueError):
            ev.eval(labels, preds, record_meta_data=["only_one"])
        assert ev.confusion is None or ev.confusion.matrix.sum() == 0

    def test_evaluate_roc_helpers(self):
        """evaluateROC / evaluateROCMultiClass model helpers (reference
        surface) on both model types."""
        ds2 = small_classification_data(n_classes=2)
        conf = mlp_conf(n_classes=2)
        net = MultiLayerNetwork(conf).init()
        net.fit(ds2, epochs=5, batch_size=32)
        roc = net.evaluate_roc(ds2)
        assert 0.5 <= roc.calculate_auc() <= 1.0
        rocm = net.evaluate_roc_multi_class(ds2)
        assert 0.0 <= rocm.calculate_average_auc() <= 1.0

        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration as NNC
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        gconf = (
            NNC.builder().seed(1).updater(Adam(0.02)).weight_init("xavier")
            .graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("o", OutputLayer(n_out=2, activation="softmax",
                                        loss="mcxent"), "d")
            .set_outputs("o")
            .set_input_types(InputType.feed_forward(4)).build()
        )
        g = ComputationGraph(gconf).init()
        g.fit(ds2, batch_size=32)
        assert 0.0 <= g.evaluate_roc(ds2).calculate_auc() <= 1.0


class TestSummary:
    def test_summary_table(self):
        """reference MultiLayerNetwork.summary():3230 — layer table with
        per-layer and total param counts."""
        from deeplearning4j_tpu.models.lenet import LeNet

        net = LeNet(num_classes=10).init()
        s = net.summary()
        assert "ConvolutionLayer" in s and "OutputLayer" in s
        assert f"Total parameters: {net.num_params():,}" in s
        assert s.count("\n") >= 7


class TestConvenienceAPI:
    """predict / f1_score / score_examples / layer_size /
    rnn_get+set_previous_state / set_learning_rate (reference
    MultiLayerNetwork public surface)."""

    def _net(self):
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n=12):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((n, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
        return DataSet(x, y)

    def test_predict_and_f1(self):
        net = self._net()
        ds = self._data()
        pred = net.predict(ds.features)
        assert pred.shape == (12,) and pred.dtype.kind == "i"
        assert set(pred) <= {0, 1, 2}
        f1 = net.f1_score(ds)
        assert 0.0 <= f1 <= 1.0

    def test_score_examples_matches_mean_score(self):
        net = self._net()
        ds = self._data()
        per_ex = net.score_examples(ds, add_regularization_terms=False)
        assert per_ex.shape == (12,)
        np.testing.assert_allclose(per_ex.mean(), net.score(ds), rtol=1e-5)

    def test_layer_size(self):
        net = self._net()
        assert net.layer_size(0) == 6 and net.layer_size(1) == 3

    def test_set_learning_rate_changes_step(self):
        ds = self._data()
        a, b = self._net(), self._net()
        a.fit(ds, epochs=1, batch_size=12)
        b.set_learning_rate(0.0)
        # materialize to host: the jitted step donates the param buffers
        p_before = [{k: np.asarray(v) for k, v in p.items()}
                    for p in b.params_]
        b.fit(ds, epochs=1, batch_size=12)
        for p0, p1 in zip(p_before, b.params_):
            for k in p0:
                np.testing.assert_array_equal(np.asarray(p0[k]),
                                              np.asarray(p1[k]))
        # and the lr=0.1 run did move
        assert any(
            not np.array_equal(np.asarray(pa[k]), np.asarray(pb[k]))
            for pa, pb in zip(a.params_, p_before) for k in pa
        )

    def test_set_learning_rate_isolated_between_networks(self):
        """Two networks built from ONE conf object must not share updater
        state: set_learning_rate(0) on one leaves the other training
        (ADVICE r3: networks held references to the conf's layer objects,
        so retuning one silently retuned its sibling)."""
        ds = self._data()
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        frozen = MultiLayerNetwork(conf).init()
        live = MultiLayerNetwork(conf).init()
        frozen.set_learning_rate(0.0)
        # the conf's own layers are untouched too
        conf_lrs = [float(l.updater.learning_rate.value_at(0, 0))
                    for l in conf.layers if l.updater is not None]
        np.testing.assert_allclose(conf_lrs, 0.1, rtol=1e-6)
        p_before = [{k: np.asarray(v) for k, v in p.items()}
                    for p in live.params_]
        live.fit(ds, epochs=1, batch_size=12)
        assert any(
            not np.array_equal(p0[k], np.asarray(p1[k]))
            for p0, p1 in zip(p_before, live.params_) for k in p0)

    def test_rnn_state_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.layers import LSTM, RnnOutputLayer

        conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                .list()
                .layer(LSTM(n_out=5))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x1 = rng.standard_normal((2, 4, 3)).astype(np.float32)
        x2 = rng.standard_normal((2, 4, 3)).astype(np.float32)
        net.rnn_time_step(x1)
        saved = net.rnn_get_previous_state()
        out_a = net.rnn_time_step(x2)
        # restore and replay: identical continuation
        net.rnn_set_previous_state(saved)
        out_b = net.rnn_time_step(x2)
        np.testing.assert_allclose(out_a, out_b, rtol=1e-6, atol=1e-7)
        net.rnn_clear_previous_state()
        assert net.rnn_get_previous_state() is None


class TestToComputationGraph:
    def test_outputs_match_after_conversion(self):
        """reference MultiLayerNetwork.toComputationGraph(): converted
        graph produces identical outputs and keeps training."""
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer,
            SubsamplingLayer,
        )

        conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.05))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((5, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 5)]
        net.fit(DataSet(x, y), epochs=2, batch_size=5)

        cg = net.to_computation_graph()
        np.testing.assert_allclose(net.output(x), cg.output_single(x),
                                   rtol=1e-5, atol=1e-6)
        # converted graph keeps training (updater state carried over)
        s0 = cg.score(DataSet(x, y))
        cg.fit(DataSet(x, y), epochs=3, batch_size=5)
        assert cg.score(DataSet(x, y)) < s0


class TestPredictionRecording:
    def test_record_meta_data_error_inspection(self):
        """reference eval/meta/Prediction surface: eval with
        record_meta_data records per-example predictions; error and
        per-class getters + merge carry them."""
        from deeplearning4j_tpu.evaluation import Evaluation

        labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        preds = np.eye(3, dtype=np.float32)[[0, 2, 2, 1]]
        ev = Evaluation()
        ev.eval(labels, preds, record_meta_data=["r0", "r1", "r2", "r3"])
        errs = ev.get_prediction_errors()
        assert [(e.actual, e.predicted, e.record_meta_data)
                for e in errs] == [(1, 2, "r1"), (0, 1, "r3")]
        assert [p.record_meta_data
                for p in ev.get_predictions_by_actual_class(0)] == [
                    "r0", "r3"]
        assert [p.record_meta_data
                for p in ev.get_predictions_by_predicted_class(2)] == [
                    "r1", "r2"]

        # mask filters metadata in step
        ev2 = Evaluation()
        mask = np.asarray([1, 0, 1, 1], np.float32)
        ev2.eval(labels, preds, mask=mask,
                 record_meta_data=["r0", "r1", "r2", "r3"])
        assert [p.record_meta_data for p in ev2.get_prediction_errors()] \
            == ["r3"]

        # distributed merge carries recorded predictions
        ev.merge(ev2)
        assert len(ev.get_prediction_errors()) == 3

        with pytest.raises(ValueError, match="entries"):
            Evaluation().eval(labels, preds, record_meta_data=["only_one"])
