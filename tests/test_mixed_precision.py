"""Mixed-precision (compute_dtype=bfloat16) correctness tests on the CPU
mesh.

The bf16 path is load-bearing for the headline benchmark (bench.py trains
ResNet-50 with bf16 compute, fp32 master weights) — these tests pin its
semantics without TPU hardware, mirroring the reference's
fast-path-vs-builtin validation pattern (``ValidateCudnnLSTM.java``,
``CuDNNGradientChecks.java``: the accelerated path is checked numerically
against the reference implementation; SURVEY.md §4.6, §7 hard-part 2).

Covers:
- cast-policy unit tests: norm/output layers exempt, other float params
  cast, int params untouched;
- gradients arrive fp32 at the updater (master-weight invariant);
- bf16-vs-fp32 loss-trajectory parity over 20+ steps for a CNN MLN, an
  LSTM MLN, and a ComputationGraph;
- a Keras-imported model run under compute_dtype=bfloat16 matching its
  fp32 golden outputs at bf16 tolerance.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    InputType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import (
    MultiLayerNetwork,
    _cast_layer_params_for_compute,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.updaters import Adam, Sgd

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "keras")


# --------------------------------------------------------------------------
# data helpers
# --------------------------------------------------------------------------
def _cnn_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8, 8, 1)).astype(np.float32)
    cls = (x.mean(axis=(1, 2, 3)) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[cls]
    return DataSet(x, y)


def _seq_data(n=32, T=7, nin=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, T, nin)).astype(np.float32)
    cls = (x[:, :, 0] > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[cls]  # (n, T, 2) per-timestep labels
    return DataSet(x, y)


def _cnn_conf(compute_dtype=None, seed=7):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.05))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    return (
        b.weight_init("xavier")
        .list()
        .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), padding=(1, 1),
                                activation="relu"))
        .layer(BatchNormalization())
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional(8, 8, 1))
        .build()
    )


def _lstm_conf(compute_dtype=None, seed=7):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    return (
        b.weight_init("xavier")
        .list()
        .layer(LSTM(n_out=12, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(5, 7))
        .build()
    )


def _graph_net(compute_dtype=None, seed=7):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.05))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    conf = (
        b.weight_init("xavier")
        .graph_builder()
        .add_inputs("in")
        .add_layer("d0", DenseLayer(n_out=16, activation="relu"), "in")
        .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "d0")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"), "d1")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    )
    return ComputationGraph(conf).init()


def _ff_data(n=64, nin=6, ncls=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((ncls, nin)) * 2
    cls = rng.integers(0, ncls, n)
    x = (centers[cls] + rng.standard_normal((n, nin)) * 0.3).astype(np.float32)
    y = np.eye(ncls, dtype=np.float32)[cls]
    return DataSet(x, y)


def _trajectory(net, ds, steps, batch=16):
    losses = []
    n = ds.features.shape[0]
    for s in range(steps):
        lo = (s * batch) % n
        hi = min(lo + batch, n)
        sub = DataSet(
            ds.features[lo:hi],
            ds.labels[lo:hi],
        )
        net.fit(sub, epochs=1, batch_size=hi - lo)
        losses.append(float(net.score_))
    return np.asarray(losses)


# --------------------------------------------------------------------------
# cast-policy unit tests
# --------------------------------------------------------------------------
class TestCastPolicy:
    def test_dense_params_cast_norm_and_output_exempt(self):
        net = MultiLayerNetwork(_cnn_conf("bfloat16")).init()
        cast = net._cast_for_compute(net.params_)
        layers = net.layers
        n = len(layers)
        for i, (layer, p) in enumerate(zip(layers, cast)):
            for k, v in p.items():
                if isinstance(layer, BatchNormalization) or i == n - 1:
                    assert v.dtype == jnp.float32, (
                        f"layer {i} ({type(layer).__name__}) param {k} must "
                        f"stay fp32, got {v.dtype}"
                    )
                elif jnp.issubdtype(net.params_[i][k].dtype, jnp.floating):
                    assert v.dtype == jnp.bfloat16, (
                        f"layer {i} param {k} should cast to bf16, got {v.dtype}"
                    )

    def test_master_params_stay_fp32_after_fit(self):
        net = MultiLayerNetwork(_cnn_conf("bfloat16")).init()
        net.fit(_cnn_data(), epochs=1, batch_size=16)
        for p in net.params_:
            for k, v in p.items():
                assert v.dtype == jnp.float32, f"master weight {k} is {v.dtype}"
        for o in net.opt_state_:
            for slots in o.values():
                for sname, s in slots.items():
                    if hasattr(s, "dtype") and jnp.issubdtype(s.dtype, jnp.floating):
                        assert s.dtype == jnp.float32

    def test_int_params_not_cast(self):
        class FakeLayer:
            pass

        p = {"W": jnp.ones((2, 2), jnp.float32), "idx": jnp.zeros((3,), jnp.int32)}
        out = _cast_layer_params_for_compute(
            FakeLayer(), p, jnp.bfloat16, is_output=False
        )
        assert out["W"].dtype == jnp.bfloat16
        assert out["idx"].dtype == jnp.int32

    def test_gradients_arrive_fp32_at_updater(self):
        """grad of an fp32 param through an internal bf16 cast is fp32 —
        the transpose of convert_element_type restores the input dtype, so
        updater math runs in full precision."""
        net = MultiLayerNetwork(_cnn_conf("bfloat16")).init()
        grads, score = net.compute_gradient_and_score(_cnn_data(n=16))
        assert np.isfinite(score)
        for g in grads:
            for k, v in g.items():
                assert v.dtype == jnp.float32, f"gradient {k} is {v.dtype}"

    def test_bn_running_stats_stay_fp32(self):
        net = MultiLayerNetwork(_cnn_conf("bfloat16")).init()
        net.fit(_cnn_data(), epochs=1, batch_size=16)
        bn_state = net.state_[1]
        for k, v in bn_state.items():
            if jnp.issubdtype(v.dtype, jnp.floating):
                assert v.dtype == jnp.float32, f"BN stat {k} is {v.dtype}"


# --------------------------------------------------------------------------
# loss-trajectory parity
# --------------------------------------------------------------------------
class TestTrajectoryParity:
    STEPS = 24

    def _assert_parity(self, l32, l16):
        assert np.all(np.isfinite(l16)), "bf16 trajectory has non-finite loss"
        # both must learn
        assert l16[-4:].mean() < l16[:4].mean()
        # trajectories track within bf16 noise (bf16 has ~3 decimal digits;
        # error compounds over steps — 15% relative envelope)
        rel = np.abs(l16 - l32) / np.maximum(np.abs(l32), 1e-3)
        assert rel.max() < 0.15, f"max relative divergence {rel.max():.3f}"

    def test_cnn_mln(self):
        ds = _cnn_data()
        l32 = _trajectory(MultiLayerNetwork(_cnn_conf(None)).init(), ds, self.STEPS)
        l16 = _trajectory(
            MultiLayerNetwork(_cnn_conf("bfloat16")).init(), ds, self.STEPS
        )
        self._assert_parity(l32, l16)

    def test_lstm_mln(self):
        ds = _seq_data()
        l32 = _trajectory(MultiLayerNetwork(_lstm_conf(None)).init(), ds, self.STEPS)
        l16 = _trajectory(
            MultiLayerNetwork(_lstm_conf("bfloat16")).init(), ds, self.STEPS
        )
        self._assert_parity(l32, l16)

    def test_computation_graph(self):
        ds = _ff_data()
        l32, l16 = [], []
        for cd, sink in ((None, l32), ("bfloat16", l16)):
            net = _graph_net(cd)
            n = ds.features.shape[0]
            for s in range(self.STEPS):
                lo = (s * 16) % n
                sub = DataSet(ds.features[lo:lo + 16], ds.labels[lo:lo + 16])
                net.fit(sub, epochs=1, batch_size=16)
                sink.append(float(net.score_))
        self._assert_parity(np.asarray(l32), np.asarray(l16))


# --------------------------------------------------------------------------
# Keras import under bf16
# --------------------------------------------------------------------------
class TestKerasImportBf16:
    def test_imported_cnn_matches_golden_at_bf16_tolerance(self):
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport

        path = os.path.join(FIXTURES, "cnn.h5")
        data = np.load(os.path.join(FIXTURES, "cnn_golden.npz"))
        x, y = data["x"], data["y"]
        net = KerasModelImport.import_keras_sequential_model_and_weights(
            path, compute_dtype="bfloat16"
        )
        out = net.output(x)
        # bf16 mantissa is 8 bits → ~2-3 decimal digits; softmax outputs
        # compare at 2e-2 absolute
        np.testing.assert_allclose(out, y, atol=2e-2, rtol=5e-2)
        # master weights still fp32
        for p in net.params_:
            for v in p.values():
                assert v.dtype == jnp.float32

    def test_imported_model_trains_under_bf16(self):
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport

        net = KerasModelImport.import_keras_sequential_model_and_weights(
            os.path.join(FIXTURES, "mlp.h5"), compute_dtype="bfloat16"
        )
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 12)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
        net.fit(DataSet(x, y), epochs=3, batch_size=16)
        assert np.isfinite(net.score())
