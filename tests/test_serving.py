"""Serving subsystem tests: bucket policy, dynamic batcher, engine
(warmup / zero-recompile steady state / atomic hot reload), the HTTP
front-end, and the ParallelInference regressions it absorbs.

Fast tier: unit coverage + a 2-bucket CPU smoke (one request through
engine and HTTP). Slow tier (@slow): multi-threaded client storms
through ParallelInference and the HTTP server asserting result
integrity, bounded compiles, typed overload rejection, and that hot
reload mid-storm never serves a mixed model.
"""

import gc
import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelInference
from deeplearning4j_tpu.serving import (
    BucketPolicy,
    DynamicBatcher,
    InferenceEngine,
    InferenceServer,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
)
from deeplearning4j_tpu.serving.buckets import IdentityBucketPolicy
from deeplearning4j_tpu.train.faults import save_checkpoint, truncate_file
from deeplearning4j_tpu.train.model_serializer import ModelSerializer


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    """This module traces ~50 small XLA programs across many short-lived
    engines; on the cramped CPU test host the executables otherwise stay
    resident for the rest of the suite (heap pressure the warm-run
    XLA:CPU flake class documented in .claude/skills/verify/SKILL.md is
    sensitive to). Drop them once the module is done — later tests build
    fresh nets and retrace anyway, with the persistent disk cache warm."""
    yield
    gc.collect()
    jax.clear_caches()


def _net(seed: int = 7, n_in: int = 4, n_out: int = 3) -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(seed)
        .list()
        .layer(DenseLayer(n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(n_in))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rows(n: int, d: int = 4, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).standard_normal((n, d)).astype(
        np.float32)


# ---------------------------------------------------------------------------
# bucket policy
# ---------------------------------------------------------------------------
class TestBucketPolicy:
    def test_pow2_default(self):
        p = BucketPolicy(max_batch=32)
        assert p.batch_buckets == [1, 2, 4, 8, 16, 32]
        # non-pow2 limit: last bucket is exactly the limit
        assert BucketPolicy(max_batch=12).batch_buckets == [1, 2, 4, 8, 12]

    def test_bucket_for(self):
        p = BucketPolicy(batch_buckets=[2, 4, 16])
        assert p.bucket_for(1) == 2
        assert p.bucket_for(4) == 4
        assert p.bucket_for(5) == 16
        # oversize grows by powers of two past the top and is REMEMBERED
        assert p.bucket_for(17) == 32
        assert p.batch_buckets[-1] == 32
        assert p.bucket_for(30) == 32  # no second growth

    def test_pad_batch_roundtrip(self):
        p = BucketPolicy(batch_buckets=[4, 8])
        x = _rows(3)
        xp, mp, n = p.pad_batch(x)
        assert xp.shape == (4, 4) and n == 3 and mp is None
        np.testing.assert_array_equal(xp[:3], x)
        np.testing.assert_array_equal(xp[3:], 0.0)
        # exact fit: no copy, same object through
        x4 = _rows(4)
        xp, _, n = p.pad_batch(x4)
        assert xp is x4 and n == 4

    def test_seq_buckets_synthesize_mask(self):
        p = BucketPolicy(batch_buckets=[4], seq_buckets=[8, 16])
        x = np.ones((2, 5, 3), np.float32)
        xp, mp, n = p.pad_batch(x)
        assert xp.shape == (4, 8, 3) and n == 2
        assert mp.shape == (2, 5) or mp.shape == (4, 8)
        # real steps masked in, padding masked out
        assert mp.shape == (4, 8)
        np.testing.assert_array_equal(mp[:2, :5], 1.0)
        assert float(mp[:2, 5:].sum()) == 0.0 and float(mp[2:].sum()) == 0.0
        # mask presence is uniform: even exact-fit input gets one
        x2 = np.ones((4, 8, 3), np.float32)
        _, mp2, _ = p.pad_batch(x2)
        assert mp2 is not None and mp2.shape == (4, 8)

    def test_warmup_shapes(self):
        p = BucketPolicy(batch_buckets=[2, 4])
        assert p.warmup_shapes((5,)) == [((2, 5), False), ((4, 5), False)]
        ps = BucketPolicy(batch_buckets=[2], seq_buckets=[8, 16])
        assert ps.warmup_shapes((5, 3)) == [((2, 8, 3), True),
                                            ((2, 16, 3), True)]

    def test_identity_policy(self):
        p = BucketPolicy.identity()
        assert isinstance(p, IdentityBucketPolicy)
        x = _rows(5)
        xp, mp, n = p.pad_batch(x)
        assert xp is x and n == 5 and mp is None
        assert p.bucket_for(7) == 7
        assert p.warmup_shapes((4,)) == []

    def test_bad_buckets_raise(self):
        with pytest.raises(ValueError):
            BucketPolicy(batch_buckets=[0, 2])
        with pytest.raises(ValueError):
            BucketPolicy(seq_buckets=[-1])

    def test_explicit_buckets_union_batch_limit(self):
        """Explicit buckets + max_batch (the batcher's batch_limit): the
        limit joins the list, so a FULL coalesced batch pads to the
        limit instead of growing past it into a never-warmed shape."""
        p = BucketPolicy(batch_buckets=[1, 4, 12], max_batch=32)
        assert p.batch_buckets == [1, 4, 12, 32]
        assert p.bucket_for(32) == 32
        # without max_batch the explicit list is taken as-is
        assert BucketPolicy(batch_buckets=[1, 4, 12]).batch_buckets == \
            [1, 4, 12]

    def test_copy_is_independent(self):
        p = BucketPolicy(batch_buckets=[2, 4], seq_buckets=[8])
        c = p.copy()
        c.batch_buckets.append(64)
        c.seq_buckets.append(16)
        assert p.batch_buckets == [2, 4] and p.seq_buckets == [8]
        assert isinstance(BucketPolicy.identity().copy(),
                          IdentityBucketPolicy)


# ---------------------------------------------------------------------------
# dynamic batcher (pure threading — no jax)
# ---------------------------------------------------------------------------
def _echo_dispatch(batch):
    for r in batch:
        r.finish(r.x * 2.0)


class TestDynamicBatcher:
    def test_dispatch_never_overshoots_batch_limit(self):
        sizes = []
        lock = threading.Lock()

        def dispatch(batch):
            with lock:
                sizes.append(sum(r.rows for r in batch))
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=8, max_wait_ms=20,
                           queue_limit=64)
        reqs = [b.submit(_rows(3, seed=i)) for i in range(10)]
        for r in reqs:
            r.result(timeout=10)
        b.shutdown()
        assert sizes and all(s <= 8 for s in sizes)
        # 3-row requests into limit 8 → at most 2 per batch, and the
        # coalescing wait window must actually pair some of them up
        assert any(s == 6 for s in sizes)

    def test_oversized_single_request_dispatches_alone(self):
        sizes = []

        def dispatch(batch):
            sizes.append(sum(r.rows for r in batch))
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=4, max_wait_ms=1)
        out = b.submit(_rows(9)).result(timeout=10)
        assert out.shape[0] == 9 and sizes == [9]
        b.shutdown()

    def test_max_wait_dispatches_partial_batch(self):
        b = DynamicBatcher(_echo_dispatch, batch_limit=64, max_wait_ms=10)
        t0 = time.monotonic()
        out = b.submit(_rows(2)).result(timeout=10)
        assert time.monotonic() - t0 < 5.0  # served well before any limit
        np.testing.assert_allclose(out, _rows(2) * 2.0)
        b.shutdown()

    def test_overload_rejects_typed(self):
        release = threading.Event()

        def dispatch(batch):
            release.wait(10)
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=1, max_wait_ms=0,
                           queue_limit=2)
        first = b.submit(_rows(1))  # worker takes this, blocks in dispatch
        time.sleep(0.1)
        held = [b.submit(_rows(1)) for _ in range(2)]  # queue now full
        with pytest.raises(ServerOverloadedError):
            b.submit(_rows(1))
        assert b.metrics.rejects == 1
        release.set()
        for r in [first] + held:
            r.result(timeout=10)
        b.shutdown()

    def test_shutdown_drains_then_rejects(self):
        release = threading.Event()

        def dispatch(batch):
            release.wait(10)
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=1, max_wait_ms=0,
                           queue_limit=8)
        queued = [b.submit(_rows(1, seed=i)) for i in range(4)]
        release.set()
        b.shutdown(drain=True)
        for r in queued:  # drain SERVED them, not failed them
            assert r.result(timeout=1).shape == (1, 4)
        with pytest.raises(ServerShutdownError):
            b.submit(_rows(1))

    def test_no_caller_blocks_forever_across_shutdown_race(self):
        """Producers hammering submit() while shutdown runs: every
        producer thread must terminate with either a result or a typed
        ServingError — the old put-after-drain hang is impossible."""
        b = DynamicBatcher(_echo_dispatch, batch_limit=4, max_wait_ms=1,
                           queue_limit=8)
        outcomes = []
        lock = threading.Lock()

        def producer(i):
            try:
                out = b.submit(_rows(1, seed=i)).result(timeout=5)
                with lock:
                    outcomes.append(("ok", out.shape))
            except (ServerShutdownError, ServerOverloadedError,
                    RequestDeadlineExceeded) as e:
                with lock:
                    outcomes.append(("err", type(e).__name__))

        threads = [threading.Thread(target=producer, args=(i,))
                   for i in range(16)]
        for i, t in enumerate(threads):
            t.start()
            if i == 7:
                b.shutdown(drain=True)
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        assert len(outcomes) == 16

    def test_result_timeout_raises_typed(self):
        def dispatch(batch):
            time.sleep(0.5)
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=1, max_wait_ms=0)
        req = b.submit(_rows(1))
        with pytest.raises(RequestDeadlineExceeded):
            req.result(timeout=0.05)
        # the typed error is also a TimeoutError for generic callers
        assert issubclass(RequestDeadlineExceeded, TimeoutError)
        b.shutdown()

    def test_queued_deadline_dropped_not_dispatched(self):
        release = threading.Event()

        def dispatch(batch):
            release.wait(10)
            _echo_dispatch(batch)

        b = DynamicBatcher(dispatch, batch_limit=1, max_wait_ms=0,
                           queue_limit=8)
        b.submit(_rows(1))  # occupies the worker
        time.sleep(0.05)
        doomed = b.submit(_rows(1), timeout=0.01)  # expires while queued
        time.sleep(0.1)
        release.set()
        with pytest.raises(RequestDeadlineExceeded):
            doomed.result(timeout=5)
        assert b.metrics.deadline_exceeded >= 1
        b.shutdown()

    def test_dispatch_error_propagates_to_all_callers(self):
        def dispatch(batch):
            raise ValueError("boom")

        b = DynamicBatcher(dispatch, batch_limit=8, max_wait_ms=5)
        reqs = [b.submit(_rows(1, seed=i)) for i in range(3)]
        for r in reqs:
            with pytest.raises(ValueError, match="boom"):
                r.result(timeout=5)
        b.shutdown()


# ---------------------------------------------------------------------------
# inference engine
# ---------------------------------------------------------------------------
class TestInferenceEngine:
    def test_two_bucket_smoke(self):
        """Tier-1 smoke: one request through a 2-bucket engine on CPU."""
        net = _net()
        eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[2, 4]))
        rep = eng.warmup()
        assert rep["shapes"] == 2 and eng.warm
        x = _rows(3)
        np.testing.assert_allclose(eng.infer(x), net.output(x), atol=1e-6)

    def test_warmup_then_steady_state_zero_compiles(self):
        """The acceptance property: after warmup(), mixed request sizes
        cause ZERO new XLA compilations (compile-count hook)."""
        net = _net()
        eng = InferenceEngine(net,
                              buckets=BucketPolicy(batch_buckets=[1, 2, 4, 8]))
        rep = eng.warmup()
        assert rep["compiles"] == 4  # one program per bucket
        assert eng.compile_count == 4
        ref = {n: net.output(_rows(n, seed=n)) for n in range(1, 9)}
        for n in (3, 1, 8, 5, 2, 7, 4, 6, 3, 8, 1):
            out = eng.infer(_rows(n, seed=n))
            # padding never leaks: bucketed result == direct forward
            np.testing.assert_allclose(out, ref[n], atol=1e-6)
        assert eng.compile_count == 4  # steady state compiled NOTHING

    def test_naive_coalescing_compiles_per_size(self):
        """The A/B control: identity buckets compile one program per
        distinct size — the failure mode the policy removes."""
        net = _net()
        eng = InferenceEngine(net, buckets=BucketPolicy.identity())
        for n in (1, 2, 3, 4, 5):
            eng.infer(_rows(n))
        assert eng.compile_count == 5

    def test_oversize_grows_bucket_once(self):
        net = _net()
        eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[2]))
        eng.warmup()
        c0 = eng.compile_count
        eng.infer(_rows(5))  # grows a 8-bucket → one compile
        eng.infer(_rows(7))  # same grown bucket → none
        assert eng.compile_count == c0 + 1

    def test_mesh_bucket_divisibility_enforced(self):
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh

        mesh = TrainingMesh(data=8)
        # nothing divisible → hard error with guidance
        with pytest.raises(ValueError, match="divisible"):
            InferenceEngine(_net(), mesh=mesh,
                            buckets=BucketPolicy(batch_buckets=[2, 4]))
        # partially divisible → non-divisible buckets dropped with a
        # warning (the default pow2 list always contains 1, 2, 4...)
        with pytest.warns(UserWarning, match="dropping"):
            filtered = InferenceEngine(_net(), mesh=mesh,
                                       buckets=BucketPolicy(max_batch=16))
        assert filtered.buckets.batch_buckets == [8, 16]
        eng = InferenceEngine(_net(), mesh=mesh,
                              buckets=BucketPolicy(batch_buckets=[8, 16]))
        eng.warmup()
        x = _rows(3)
        np.testing.assert_allclose(eng.infer(x), eng.model.output(x),
                                   atol=1e-6)

    def test_hot_reload_same_arch_zero_compiles(self, tmp_path):
        net = _net(seed=1)
        eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[4]))
        eng.warmup()
        c0 = eng.compile_count
        v0 = eng.model_version

        # same conf (the retrained-checkpoint case), different weights
        other = _net(seed=1)
        other.set_params_flat(other.params_flat() + 0.25)
        ckpt = str(tmp_path / "m.zip")
        ModelSerializer.write_model(other, ckpt)
        result = eng.reload(ckpt)
        assert result["reloaded"] and result["same_arch"]
        assert eng.model_version == v0 + 1
        assert eng.compile_count == c0  # pure weight swap
        x = _rows(3)
        np.testing.assert_allclose(eng.infer(x), other.output(x), atol=1e-6)

    def test_reload_unchanged_is_noop(self, tmp_path):
        ckpt_dir = str(tmp_path)
        save_checkpoint(_net(seed=5), ckpt_dir)
        eng = InferenceEngine.from_checkpoint(ckpt_dir)
        result = eng.reload()
        assert result["reloaded"] is False and result["reason"] == "unchanged"
        result = eng.reload(force=True)
        assert result["reloaded"] is True

    def test_reload_skips_corrupt_newest(self, tmp_path):
        ckpt_dir = str(tmp_path)
        good = _net(seed=5)
        p1 = save_checkpoint(good, ckpt_dir, stem="ckpt_a")
        eng = InferenceEngine.from_checkpoint(ckpt_dir)
        time.sleep(0.02)
        p2 = save_checkpoint(_net(seed=6), ckpt_dir, stem="ckpt_b")
        truncate_file(p2)  # crash-mid-write debris
        with pytest.warns(UserWarning, match="corrupt"):
            result = eng.reload(force=True)
        assert result["path"] == p1  # fell back to the valid one
        x = _rows(2)
        np.testing.assert_allclose(eng.infer(x), good.output(x), atol=1e-6)

    def test_seq_buckets_rnn_pad_and_unpad(self):
        """Sequence-length bucketing on a recurrent model: the time dim
        pads up to the bucket under a synthesized mask and slices back
        out of per-timestep outputs; zoo models carry the bucket hint."""
        from deeplearning4j_tpu.models.textgen_lstm import TextGenerationLSTM

        assert TextGenerationLSTM.serving_seq_buckets == (8, 16, 32, 64)
        zoo = TextGenerationLSTM(num_classes=6, units=4, max_length=16)
        net = zoo.init()
        pol = zoo.serving_bucket_policy(batch_buckets=[2], max_batch=2)
        assert pol.seq_buckets == [8, 16, 32, 64]
        assert zoo.serving_input_shape() == (1, 6)
        pol.seq_buckets = [8, 16]  # trim for test speed
        pol.batch_buckets = [2]
        eng = InferenceEngine(net, buckets=pol)
        assert eng.warmup()["shapes"] == 2
        c0 = eng.compile_count
        x = np.random.default_rng(0).standard_normal((1, 11, 6)).astype(
            np.float32)
        out = eng.infer(x)
        assert out.shape == (1, 11, 6)  # T sliced back from the 16-bucket
        ref = net.output(x, mask=np.ones((1, 11), np.float32))
        np.testing.assert_allclose(out, ref, atol=1e-5)
        assert eng.compile_count == c0  # mixed-T steady state: no compiles

    def test_from_checkpoint_zip_and_describe(self, tmp_path):
        net = _net(seed=3)
        ckpt = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, ckpt)
        eng = InferenceEngine.from_checkpoint(ckpt)
        info = eng.describe()
        assert info["model_type"] == "MultiLayerNetwork"
        assert info["version"] == 0 and info["source"] == ckpt
        x = _rows(2)
        np.testing.assert_allclose(eng.infer(x), net.output(x), atol=1e-6)

    def test_engine_copies_policy(self):
        """Two engines sharing one policy object must not see each
        other's mesh filtering or oversize growth."""
        pol = BucketPolicy(batch_buckets=[2])
        a = InferenceEngine(_net(), buckets=pol)
        a.infer(_rows(5))  # grows a's copy to [2, 8]
        assert pol.batch_buckets == [2]
        b = InferenceEngine(_net(), buckets=pol)
        assert b.buckets.batch_buckets == [2]

    def test_selector_load_or_init_branches(self, tmp_path):
        """zoo name / checkpoint zip / checkpoint dir all resolve (the
        serve CLI's model-source surface)."""
        from deeplearning4j_tpu.models.selector import ModelSelector

        net = _net(seed=8)
        d = str(tmp_path)
        p = save_checkpoint(net, d)
        m1, o1 = ModelSelector.load_or_init(p)  # zip
        assert o1 == p
        np.testing.assert_allclose(m1.params_flat(), net.params_flat())
        m2, o2 = ModelSelector.load_or_init(d)  # dir → newest valid
        assert o2 == p
        m3, o3 = ModelSelector.load_or_init("lenet", num_classes=5)  # zoo
        assert o3 == "lenet" and m3.num_params() > 0
        with pytest.raises(ValueError, match="neither"):
            ModelSelector.load_or_init(str(tmp_path / "nope"))


# ---------------------------------------------------------------------------
# ParallelInference regressions (the satellites it absorbs)
# ---------------------------------------------------------------------------
class _ShapeRecorder:
    """Model proxy recording every dispatched batch's row count."""

    def __init__(self, net):
        self._net = net
        self.dispatched = []
        self._lock = threading.Lock()

    def output(self, x, mask=None):
        with self._lock:
            self.dispatched.append(int(np.asarray(x).shape[0]))
        return self._net.output(x, mask=mask)


class TestParallelInferenceRegressions:
    def test_batch_limit_never_overshoots(self):
        """Old loop: checked total < limit BEFORE pulling the next
        request, dispatching up to limit+rows-1. Now a request that
        would overflow stays queued."""
        rec = _ShapeRecorder(_net())
        pi = (ParallelInference.builder(rec).batch_limit(8)
              .buckets(False).max_wait_ms(30).build())
        results = {}

        def call(i):
            results[i] = pi.output(_rows(3, seed=i))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(9)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        pi.shutdown()
        assert rec.dispatched and all(n <= 8 for n in rec.dispatched)
        for i in range(9):
            assert results[i].shape == (3, 3)

    def test_bucketed_dispatch_shapes(self):
        """Default buckets quantize dispatches to powers of two."""
        rec = _ShapeRecorder(_net())
        pi = ParallelInference.builder(rec).batch_limit(8).build()
        out = pi.output(_rows(3))
        assert out.shape == (3, 3)
        assert rec.dispatched == [4]  # 3 rows padded up to the 4-bucket
        # the facade records latency quantiles like the HTTP server does
        assert pi.metrics.snapshot()["latency_p50_ms"] is not None
        pi.shutdown()

    def test_output_timeout(self):
        net = _net()
        slow = _ShapeRecorder(net)
        real_output = slow.output

        def stalling(x, mask=None):
            time.sleep(0.5)
            return real_output(x, mask=mask)

        slow.output = stalling
        pi = ParallelInference.builder(slow).build()
        with pytest.raises(TimeoutError):
            pi.output(_rows(1), timeout=0.05)
        pi.shutdown()

    def test_shutdown_then_output_raises(self):
        pi = ParallelInference.builder(_net()).build()
        assert pi.output(_rows(2)).shape == (2, 3)
        pi.shutdown()
        with pytest.raises(RuntimeError):
            pi.output(_rows(2))

    def test_overload_is_typed(self):
        rec = _ShapeRecorder(_net())
        release = threading.Event()
        entered = threading.Event()
        real_output = rec.output

        def blocking(x, mask=None):
            entered.set()
            release.wait(10)
            return real_output(x, mask=mask)

        rec.output = blocking
        pi = (ParallelInference.builder(rec).batch_limit(1)
              .queue_limit(2).max_wait_ms(0).build())
        held = [threading.Thread(target=lambda i=i: pi.output(_rows(1, seed=i)))
                for i in range(3)]
        # deterministic overload state (a fixed sleep flakes under box
        # load, and starting all three at once races the queue_limit=2
        # bound against the worker's dequeue — under contention a SETUP
        # thread can absorb the 503 meant for the probe): first occupy
        # the worker, THEN fill the queue with the other two
        held[0].start()
        assert entered.wait(10)  # worker is BLOCKED inside the dispatch
        for t in held[1:]:
            t.start()
        deadline = time.monotonic() + 10
        while (pi._batcher.queue_depth() < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert pi._batcher.queue_depth() == 2
        with pytest.raises(ServerOverloadedError):
            pi.output(_rows(1))
        release.set()
        for t in held:
            t.join(timeout=10)
        pi.shutdown()


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------
def _http(port, method, path, body=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path,
                     None if body is None else
                     (body if isinstance(body, bytes) else json.dumps(body)))
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, raw
    finally:
        conn.close()


@pytest.fixture
def served():
    net = _net(seed=21)
    eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[2, 4, 8]))
    eng.warmup()
    server = InferenceServer(eng, port=0, batch_limit=8, max_wait_ms=2,
                             queue_limit=32).start()
    yield net, eng, server
    server.shutdown()


class TestInferenceServer:
    def test_predict_json(self, served):
        net, _, server = served
        x = _rows(3, seed=2)
        status, body = _http(server.port, "POST", "/predict",
                             {"inputs": x.tolist()})
        assert status == 200
        np.testing.assert_allclose(np.asarray(body["outputs"]),
                                   net.output(x), atol=1e-5)
        assert body["model_version"] == 0
        # single-example convenience: 1-D input auto-batches
        status, body = _http(server.port, "POST", "/predict",
                             {"inputs": x[0].tolist()})
        assert status == 200 and len(body["outputs"]) == 1

    def test_predict_npy_roundtrip(self, served):
        import io

        net, _, server = served
        x = _rows(5, seed=3)
        buf = io.BytesIO()
        np.save(buf, x)
        status, raw = _http(server.port, "POST", "/predict_npy",
                            buf.getvalue())
        assert status == 200
        out = np.load(io.BytesIO(raw))
        np.testing.assert_allclose(out, net.output(x), atol=1e-5)

    def test_healthz_and_metrics(self, served):
        _, eng, server = served
        status, health = _http(server.port, "GET", "/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["model_type"] == "MultiLayerNetwork" and health["warm"]
        _http(server.port, "POST", "/predict",
              {"inputs": _rows(2).tolist()})
        status, m = _http(server.port, "GET", "/metrics")
        assert status == 200
        assert m["requests"] >= 1 and m["dispatches"] >= 1
        assert "queue_depth" in m and m["latency_p50_ms"] is not None
        assert any(int(k) in (2, 4, 8) for k in m["bucket_hits"])

    def test_bad_payload_400_unknown_404(self, served):
        _, _, server = served
        status, body = _http(server.port, "POST", "/predict", {"wrong": 1})
        assert status == 400 and body["error"] == "ValueError"
        # empty npy body is the CLIENT's fault: 400, not 500
        status, body = _http(server.port, "POST", "/predict_npy", b"")
        assert status == 400 and body["error"] == "ValueError"
        status, _ = _http(server.port, "GET", "/nope")
        assert status == 404
        status, _ = _http(server.port, "POST", "/nope")
        assert status == 404

    def test_overload_returns_503(self, served):
        _, eng, server = served
        release = threading.Event()
        real_infer = eng.infer_versioned

        def blocking_infer(x, mask=None):
            release.wait(10)
            return real_infer(x, mask)

        eng.infer_versioned = blocking_infer
        try:
            # tiny queue for the test
            server.batcher._queue.maxsize = 2
            statuses = []
            lock = threading.Lock()

            def post():
                s, _ = _http(server.port, "POST", "/predict",
                             {"inputs": _rows(1).tolist()})
                with lock:
                    statuses.append(s)

            threads = [threading.Thread(target=post) for _ in range(8)]
            for t in threads:
                t.start()
                time.sleep(0.02)
            time.sleep(0.2)
            release.set()
            for t in threads:
                t.join(timeout=15)
            assert 503 in statuses  # backpressure surfaced as HTTP 503
            assert 200 in statuses  # accepted requests still served
        finally:
            eng.infer_versioned = real_infer
            release.set()

    def test_reload_endpoint(self, served, tmp_path):
        net, eng, server = served
        other = _net(seed=21)  # same conf as the served model
        other.set_params_flat(other.params_flat() + 0.25)
        ckpt = str(tmp_path / "new.zip")
        ModelSerializer.write_model(other, ckpt)
        status, body = _http(server.port, "POST", "/reload", {"path": ckpt})
        assert status == 200 and body["reloaded"] and body["same_arch"]
        x = _rows(2, seed=9)
        status, out = _http(server.port, "POST", "/predict",
                            {"inputs": x.tolist()})
        assert out["model_version"] == body["version"]
        np.testing.assert_allclose(np.asarray(out["outputs"]),
                                   other.output(x), atol=1e-5)
        # unchanged → no-op
        status, body2 = _http(server.port, "POST", "/reload", {"path": ckpt})
        assert status == 200 and body2["reloaded"] is False
        # missing source → 409, serving unaffected
        status, _ = _http(server.port, "POST", "/reload",
                          {"path": str(tmp_path / "missing")})
        assert status in (400, 409)

    def test_cli_serve_smoke(self):
        """Satellite smoke: one request through `cli serve` end to end
        (2-bucket engine, ephemeral port, CPU)."""
        from deeplearning4j_tpu.cli import main

        rc = main(["serve", "--model", "lenet", "--batch-limit", "2",
                   "--port", "0", "--smoke"])
        assert rc == 0


# ---------------------------------------------------------------------------
# client storms (slow tier)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestServingStorm:
    def test_parallel_inference_storm_integrity(self):
        """Multi-threaded client storm through ParallelInference: every
        caller gets exactly its own rows back, bucket padding never
        leaks, and the compiled-program count stays at the bucket
        count."""
        net = _net(seed=4)
        pi = (ParallelInference.builder(net).batch_limit(16)
              .queue_limit(256).max_wait_ms(2).build())
        refs = {n: np.asarray(net.output(_rows(n, d=4, seed=100 + n)))
                for n in range(1, 9)}
        errors = []
        lock = threading.Lock()

        def client(tid):
            rng = np.random.default_rng(tid)
            for _ in range(20):
                n = int(rng.integers(1, 9))
                out = pi.output(_rows(n, d=4, seed=100 + n))
                if not np.allclose(out, refs[n], atol=1e-5):
                    with lock:
                        errors.append((tid, n))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        pi.shutdown()
        assert not errors

    def test_http_storm_with_hot_reload_never_mixes_models(self, tmp_path):
        """Client storm through the HTTP server while checkpoints hot-swap
        underneath: every response must match exactly ONE model version
        (all rows of a response from the same params — atomic swap), and
        steady-state traffic after warmup compiles nothing new."""
        net_a = _net(seed=1)
        net_b = _net(seed=1)  # same conf → pure weight-swap reloads
        net_b.set_params_flat(net_b.params_flat() + 0.25)
        ckpt_b = str(tmp_path / "b.zip")
        ModelSerializer.write_model(net_b, ckpt_b)

        eng = InferenceEngine(net_a,
                              buckets=BucketPolicy(batch_buckets=[2, 4, 8,
                                                                  16]))
        eng.warmup()
        compiles_after_warmup = eng.compile_count
        server = InferenceServer(eng, port=0, batch_limit=16, max_wait_ms=2,
                                 queue_limit=256).start()
        try:
            sizes = range(1, 9)
            ref_a = {n: np.asarray(net_a.output(_rows(n, seed=200 + n)))
                     for n in sizes}
            ref_b = {n: np.asarray(net_b.output(_rows(n, seed=200 + n)))
                     for n in sizes}
            mixed = []
            failures = []
            lock = threading.Lock()
            stop = threading.Event()

            def client(tid):
                rng = np.random.default_rng(tid)
                while not stop.is_set():
                    n = int(rng.integers(1, 9))
                    x = _rows(n, seed=200 + n)
                    status, body = _http(server.port, "POST", "/predict",
                                         {"inputs": x.tolist()})
                    if status != 200:
                        continue  # overload shedding is legal mid-storm
                    out = np.asarray(body["outputs"])
                    is_a = np.allclose(out, ref_a[n], atol=1e-5)
                    is_b = np.allclose(out, ref_b[n], atol=1e-5)
                    # version 0 is net_a; every reload swaps in net_b —
                    # the reported version must attribute the weights
                    # that actually computed the rows
                    ver = body["model_version"]
                    ver_ok = (is_a and ver == 0) or (is_b and ver >= 1)
                    with lock:
                        if not (is_a or is_b) or not ver_ok:
                            mixed.append((tid, n, ver))
                        if status != 200:
                            failures.append(status)

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(8)]
            for t in threads:
                t.start()
            # hot-swap a few times mid-storm
            for _ in range(3):
                time.sleep(0.4)
                eng.reload(ckpt_b, force=True)
            time.sleep(0.4)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            assert not mixed  # no response ever mixed model versions
            # acceptance: the storm (mixed sizes, reloads) compiled NOTHING
            assert eng.compile_count == compiles_after_warmup
            # and the swap really took: serving B now
            x = _rows(3, seed=203)
            np.testing.assert_allclose(eng.infer(x), ref_b[3], atol=1e-5)
        finally:
            server.shutdown()

    def test_http_overload_storm_typed_rejection(self):
        net = _net(seed=9)
        eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[4]))
        eng.warmup()
        release = threading.Event()
        real_infer = eng.infer_versioned
        eng.infer_versioned = lambda x, mask=None: (release.wait(10),
                                                    real_infer(x, mask))[1]
        server = InferenceServer(eng, port=0, batch_limit=4, max_wait_ms=0,
                                 queue_limit=4).start()
        try:
            statuses = []
            lock = threading.Lock()

            def post():
                s, body = _http(server.port, "POST", "/predict",
                                {"inputs": _rows(1).tolist()})
                with lock:
                    statuses.append((s, body.get("error")
                                     if isinstance(body, dict) else None))

            threads = [threading.Thread(target=post) for _ in range(16)]
            for t in threads:
                t.start()
            time.sleep(0.5)
            release.set()
            for t in threads:
                t.join(timeout=30)
            rejected = [e for s, e in statuses if s == 503]
            assert rejected and all(e == "ServerOverloadedError"
                                    for e in rejected)
        finally:
            eng.infer_versioned = real_infer
            release.set()
            server.shutdown()
