"""Mesh-sharded serving tests (parallel/serving_mesh.py +
serving/sharded.py, ISSUE 20).

The acceptance spine: a tensor-parallel engine on a 2x4 (batch, model)
mesh answers within float-reassociation tolerance of the replicated
engine (greedy generation EXACTLY), no device holds more than the
1/n_model + replicated share of the weights (asserted against the
memory gate's report), steady-state dispatch retraces ZERO programs,
and reshard-on-load moves any checkpoint topology onto any serving
mesh with a 0-byte host ledger. Plus the satellites: typed policy
refusals (non-divisible dims, wrong-model policies, int8 composition),
the mesh-loss solo fallback with its flight event, and canary routing
of sharded candidates through the registry unchanged.
"""

import gc
import time

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.models.transformer_lm import TransformerLM
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.serving_mesh import (
    ServingMesh,
    ShardingPolicy,
    ShardingPolicyError,
    auto_policy,
    parse_mesh_spec,
    policy_for,
    transformer_lm_policy,
    validate_policy,
)
from deeplearning4j_tpu.serving import InferenceEngine
from deeplearning4j_tpu.serving.batcher import ServingError
from deeplearning4j_tpu.serving.sharded import (
    ShardedGenerationEngine,
    ShardedInferenceEngine,
    ShardedMeshError,
    sharded_generation_engine,
)

N_IN, N_HID, N_OUT = 8, 16, 4


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    yield
    gc.collect()
    jax.clear_caches()


def _mesh24() -> ServingMesh:
    return ServingMesh(batch=2, model=4, devices=jax.devices()[:8])


def _net(seed=7) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_out=N_HID, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _rows(n=8, seed=0):
    return np.random.default_rng(seed).standard_normal(
        (n, N_IN)).astype(np.float32)


# ---------------------------------------------------------------------------
# mesh + spec grammar
# ---------------------------------------------------------------------------
class TestServingMesh:
    def test_parse_mesh_spec(self):
        assert parse_mesh_spec("2x4") == (2, 4)
        assert parse_mesh_spec("8X1") == (8, 1)
        assert parse_mesh_spec("4") == (4, 1)

    @pytest.mark.parametrize("bad", ["", "2x", "x4", "axb", "0x4", "2x-1"])
    def test_parse_mesh_spec_typed_refusal(self, bad):
        with pytest.raises(ShardingPolicyError):
            parse_mesh_spec(bad)

    def test_shape_and_axes(self):
        m = _mesh24()
        assert m.shape == {"batch": 2, "model": 4}
        assert (m.n_data, m.n_model, m.n_devices) == (2, 4, 8)
        assert len(m.devices_flat()) == 8

    def test_from_spec_and_batch_inference(self):
        m = ServingMesh.from_spec("2x4")
        assert m.shape == {"batch": 2, "model": 4}
        # batch=0 infers from the device count, TrainingMesh-style
        m = ServingMesh(model=4)
        assert m.n_data == len(jax.devices()) // 4

    def test_device_count_mismatch_typed(self):
        with pytest.raises(ShardingPolicyError, match="devices"):
            ServingMesh(batch=3, model=4, devices=jax.devices()[:8])
        with pytest.raises(ShardingPolicyError):
            ServingMesh(batch=0, model=3, devices=jax.devices()[:8])

    def test_trainingmesh_compatible_surface(self):
        m = _mesh24()
        assert m.replicated().spec == P()
        assert m.batch_sharded().spec == P("batch")
        assert m.spec(None, "model").spec == P(None, "model")


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------
class TestShardingPolicy:
    def test_policy_for_selects_bespoke_vs_auto(self):
        lm = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                           n_layers=1, max_length=16, seed=0).init()
        assert policy_for(lm).name == "transformer_lm"
        assert policy_for(_net()).name == "auto"

    def test_auto_policy_shards_matrices_replicates_vectors(self):
        m = _mesh24()
        pol = auto_policy()
        W = np.zeros((N_IN, N_HID), np.float32)
        assert pol.spec_for("0/W", W, m) == P(None, "model")
        b = np.zeros((N_HID,), np.float32)
        assert pol.spec_for("0/b", b, m) == P()

    def test_auto_policy_nondivisible_falls_back(self):
        m = _mesh24()
        pol = auto_policy()
        # last dim 3 not divisible by 4 -> shards the divisible dim
        assert pol.spec_for("1/W", np.zeros((N_HID, 3), np.float32),
                            m) == P("model", None)
        # nothing divisible -> replicate (the memory gate is the
        # backstop if such leaves dominate)
        assert pol.spec_for("1/W", np.zeros((3, 5), np.float32), m) == P()

    def test_transformer_policy_megatron_pairing(self):
        m = _mesh24()
        pol = transformer_lm_policy()
        wq = np.zeros((2, 32, 32), np.float32)
        assert pol.spec_for("blocks/Wq", wq, m) == P(None, None, "model")
        wo = np.zeros((2, 32, 32), np.float32)
        assert pol.spec_for("blocks/Wo", wo, m) == P(None, "model", None)
        assert pol.spec_for("blocks/ln1_g", np.zeros((2, 32)), m) == P()
        assert pol.spec_for("head", np.zeros((32, 64)), m) == P(
            None, "model")

    def test_mismatched_policy_typed_refusal(self):
        """A policy written for another model is a typed refusal, not a
        silent repartition: sharding a dim that does not divide."""
        m = _mesh24()
        pol = ShardingPolicy("wrong", [(r"W", P(None, "model"))])
        with pytest.raises(ShardingPolicyError, match="not divisible"):
            pol.spec_for("0/W", np.zeros((N_IN, 6), np.float32), m)

    def test_overlong_spec_typed_refusal(self):
        m = _mesh24()
        pol = ShardingPolicy("wrong", [(r"b", P(None, "model"))])
        with pytest.raises(ShardingPolicyError, match="not written"):
            pol.spec_for("0/b", np.zeros((N_HID,), np.float32), m)

    def test_policy_overrides(self):
        m = _mesh24()
        net = _net()
        pol = policy_for(net, overrides=["0/W=r"])
        assert pol.name == "auto+overrides"
        assert pol.spec_for("0/W", np.zeros((N_IN, N_HID)), m) == P()
        pol = policy_for(net, overrides=["0/W=0"])
        assert pol.spec_for("0/W", np.zeros((N_IN, N_HID)), m) == P(
            "model", None)

    @pytest.mark.parametrize("bad", ["noequals", "p=x", "p=1.5"])
    def test_bad_override_typed(self, bad):
        with pytest.raises(ShardingPolicyError, match="override"):
            policy_for(_net(), overrides=[bad])

    def test_validate_policy_report_and_estimator(self):
        net = _net()
        m = _mesh24()
        rep = validate_policy(net.params_, m, auto_policy(), conf=net.conf)
        assert rep["per_device_bytes"] <= (
            rep["total_bytes"] // m.n_model + rep["replicated_bytes"]
            + 4096)
        assert 0.5 <= rep["estimator_agreement"] <= 2.0
        assert rep["mesh"] == {"batch": 2, "model": 4}

    def test_validate_policy_memory_gate_fires(self):
        """A policy that under-shards (splits only the 2-way batch axis)
        exceeds the total/n_model + replicated bound — typed, loud."""
        net = _net()
        m = _mesh24()
        lazy = ShardingPolicy("lazy", [(r"W", P("batch", None))])
        with pytest.raises(ShardingPolicyError, match="per device"):
            validate_policy(net.params_, m, lazy, slack_bytes=0)


# ---------------------------------------------------------------------------
# sharded inference engine
# ---------------------------------------------------------------------------
class TestShardedInference:
    def test_needs_serving_mesh_typed(self):
        with pytest.raises(ShardingPolicyError, match="ServingMesh"):
            ShardedInferenceEngine(_net(), mesh=None)

    def test_int8_composition_refused_typed(self):
        with pytest.raises(ShardingPolicyError, match="int8"):
            ShardedInferenceEngine(_net(), mesh=_mesh24(),
                                   int8_serving=True)

    def test_parity_memory_and_retraces(self):
        from deeplearning4j_tpu.obs import flight

        x = _rows()
        solo = InferenceEngine(_net())
        y_solo = solo.infer(x)
        seq0 = max((e["seq"] for e in
                    flight.default_flight_recorder().events()), default=0)
        eng = ShardedInferenceEngine(_net(), mesh=_mesh24())
        y_sh = eng.infer(x)
        assert np.allclose(y_solo, y_sh, rtol=1e-5, atol=1e-6)

        # no device holds the full model: the live report obeys the gate
        rep = eng.shard_report
        assert rep["per_device_bytes"] <= (
            rep["total_bytes"] // 4 + rep["replicated_bytes"] + 4096)
        assert rep["per_device_bytes"] < rep["total_bytes"]
        # params visibly TP-sharded on the mesh
        shardings = {str(l.sharding.spec) for l in
                     jax.tree_util.tree_leaves(eng._snap.params)}
        assert any("model" in s for s in shardings)
        # reshard ledger: live placement stages zero host bytes
        assert eng.reshard_stats.host_bytes == 0
        # flight forensics
        evs = [e for e in flight.default_flight_recorder().events()
               if e["seq"] > seq0]
        kinds = [e["kind"] for e in evs]
        assert "mesh_build" in kinds and "shard_load" in kinds
        # steady state: repeated same-shape dispatches compile nothing
        c0 = eng.compile_count
        for _ in range(4):
            eng.infer(x)
        assert eng.compile_count == c0

    def test_describe_carries_shard_telemetry(self):
        eng = ShardedInferenceEngine(_net(), mesh=_mesh24())
        d = eng.describe()
        assert d["mesh"] == {"batch": 2, "model": 4}
        assert d["policy"]["name"] == "auto"
        assert d["fallback_active"] is False
        assert d["shard_report"]["total_bytes"] > 0


# ---------------------------------------------------------------------------
# reshard-on-load topology matrix
# ---------------------------------------------------------------------------
class TestReshardTopologyMatrix:
    def test_checkpoint_to_any_mesh_zero_host_bytes(self, tmp_path):
        """ck -> solo, ck -> 2x4, and a live 2x4 model -> 8x1: every leg
        answers identically and stages zero host bytes."""
        from deeplearning4j_tpu.train.faults import save_checkpoint

        ck = save_checkpoint(_net(seed=13), str(tmp_path / "ck"))
        x = _rows()
        solo = InferenceEngine.from_checkpoint(ck)
        y_ref = solo.infer(x)

        eng24 = ShardedInferenceEngine.from_checkpoint(ck, mesh=_mesh24())
        assert np.allclose(y_ref, eng24.infer(x), rtol=1e-5, atol=1e-6)
        assert eng24.reshard_stats.host_bytes == 0

        # live sharded 2x4 params -> pure-batch 8x1 mesh (the model
        # object still carries the 2x4 placement)
        mesh81 = ServingMesh(batch=8, model=1, devices=jax.devices()[:8])
        eng81 = ShardedInferenceEngine(eng24.model, mesh=mesh81)
        assert np.allclose(y_ref, eng81.infer(x), rtol=1e-5, atol=1e-6)
        assert eng81.reshard_stats.host_bytes == 0

        # degenerate 1x1 mesh: sharded serving collapses to solo
        mesh11 = ServingMesh(batch=1, model=1, devices=jax.devices()[:1])
        eng11 = ShardedInferenceEngine.from_checkpoint(ck, mesh=mesh11)
        assert np.allclose(y_ref, eng11.infer(x), rtol=1e-5, atol=1e-6)
        assert eng11.reshard_stats.host_bytes == 0


# ---------------------------------------------------------------------------
# sharded generation
# ---------------------------------------------------------------------------
class TestShardedGeneration:
    def _lm(self, seed=9):
        return TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, max_length=48, seed=seed).init()

    def test_greedy_parity_slab_sharding_and_retraces(self):
        from deeplearning4j_tpu.serving.generate import GenerationEngine

        prompt = np.asarray([5, 9, 11, 2])
        solo = GenerationEngine(self._lm(), n_slots=4, max_length=48)
        try:
            toks_solo = list(solo.submit(prompt, max_new=10,
                                         temperature=0.0).result(
                                             timeout=120))
        finally:
            solo.shutdown()
        eng = sharded_generation_engine(self._lm(), _mesh24(), n_slots=4,
                                        max_length=48)
        try:
            assert "model" in str(eng.backend._kc.sharding.spec)
            toks = list(eng.submit(prompt, max_new=10,
                                   temperature=0.0).result(timeout=240))
            assert toks == toks_solo  # greedy decode is EXACT
            tc0 = dict(eng.trace_counts)
            toks2 = list(eng.submit(prompt, max_new=10,
                                    temperature=0.0).result(timeout=240))
            tc1 = dict(eng.trace_counts)
            assert toks2 == toks
            assert all(tc1.get(k, 0) == tc0.get(k, 0) for k in tc1
                       if k.startswith("generation_"))
            assert eng.shard_stats.host_bytes == 0
        finally:
            eng.shutdown()

    def test_slab_stays_sharded_across_reset(self):
        eng = sharded_generation_engine(self._lm(), _mesh24(), n_slots=4,
                                        max_length=48)
        try:
            eng.backend.reset()
            assert "model" in str(eng.backend._kc.sharding.spec)
            assert "batch" in str(eng.backend._vc.sharding.spec)
        finally:
            eng.shutdown()

    def test_recurrent_model_typed_refusal(self):
        with pytest.raises(ShardingPolicyError, match="TransformerLM"):
            sharded_generation_engine(_net(), _mesh24(), n_slots=4)

    def test_nondivisible_slab_typed_refusal(self):
        with pytest.raises(ShardingPolicyError, match="n_slots"):
            sharded_generation_engine(self._lm(), _mesh24(), n_slots=3,
                                      max_length=48)

    def test_factory_class_refuses_direct_construction(self):
        with pytest.raises(TypeError, match="sharded_generation_engine"):
            ShardedGenerationEngine()


# ---------------------------------------------------------------------------
# mesh-loss fallback
# ---------------------------------------------------------------------------
class TestMeshLossFallback:
    def test_error_is_typed_serving_error(self):
        assert issubclass(ShardedMeshError, ServingError)

    def test_mesh_loss_arms_solo_fallback(self):
        from deeplearning4j_tpu.chaos import ChaosPlan
        from deeplearning4j_tpu.chaos import hooks
        from deeplearning4j_tpu.obs import flight

        x = _rows()
        eng = ShardedInferenceEngine(_net(seed=3), mesh=_mesh24())
        y_healthy = eng.infer(x)
        seq0 = max((e["seq"] for e in
                    flight.default_flight_recorder().events()), default=0)
        plan = ChaosPlan([{"seam": "serving.sharded_dispatch",
                           "mode": "error"}])
        try:
            with plan.armed():
                with pytest.raises(ShardedMeshError, match="solo fallback"):
                    eng.infer(x)
        finally:
            hooks.reset()
        assert eng.fallback_active
        # the engine survives degraded: one-device serving, same answers
        assert np.allclose(y_healthy, eng.infer(x), rtol=1e-5, atol=1e-6)
        evs = [e for e in flight.default_flight_recorder().events()
               if e["seq"] > seq0 and e["kind"] == "sharded_fallback"]
        assert len(evs) == 1
        assert evs[0]["reason"] == "InjectedFaultError"


# ---------------------------------------------------------------------------
# registry: canary routing of sharded candidates
# ---------------------------------------------------------------------------
class TestRegistryShardedCandidates:
    def test_router_serves_and_promotes_sharded_versions(self, tmp_path):
        from deeplearning4j_tpu.serving.registry import (
            ModelRegistry,
            ModelRouter,
        )
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        ck1 = save_checkpoint(_net(seed=1), str(tmp_path / "ck1"))
        ck2 = save_checkpoint(_net(seed=2), str(tmp_path / "ck2"))
        reg.publish("m", ck1, score=0.5)
        router = ModelRouter(reg, mesh=_mesh24(), canary_fraction=1.0,
                             canary_window_s=0.2, canary_min_requests=1,
                             refresh_s=0.0, max_wait_ms=1.0)
        try:
            x = _rows(2)
            out = router.predict("m", x, timeout=30)
            assert out is not None
            live = router._live.get("m")
            assert isinstance(live.active.engine, ShardedInferenceEngine)
            # a sharded v2 canary promotes through the stock machinery
            reg.publish("m", ck2, score=0.45)
            deadline = time.monotonic() + 30
            promoted = False
            while time.monotonic() < deadline and not promoted:
                router.predict("m", x, timeout=30)
                time.sleep(0.05)
                promoted = reg.get("m").get("active_version") == 2
            assert promoted
        finally:
            router.shutdown()
