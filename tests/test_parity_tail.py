"""Parity-tail tests (VERDICT r2 item 8): CG tBPTT + rnnTimeStep,
ParallelWrapper partial-batch weighting + tBPTT, dropout variants /
weight noise, legacy full-batch solvers, threshold-encoded gradient
compression.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    AlphaDropout,
    DenseLayer,
    DropConnect,
    GaussianDropout,
    GaussianNoise,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    WeightNoise,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.updaters import Adam, Sgd


def _seq_data(n=16, T=12, nin=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, T, nin)).astype(np.float32)
    cls = (np.cumsum(x[:, :, 0], 1) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[cls]
    return DataSet(x, y)


def _rnn_graph(tbptt=False, seed=5):
    b = (
        NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
        .weight_init("xavier").graph_builder()
        .add_inputs("in")
        .add_layer("lstm", LSTM(n_out=8, activation="tanh"), "in")
        .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"), "lstm")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(3, 12))
    )
    if tbptt:
        b = b.backprop_type("tbptt", fwd_length=4, back_length=4)
    return ComputationGraph(b.build()).init()


class TestCGtBPTT:
    def test_tbptt_trains_and_reduces_loss(self):
        net = _rnn_graph(tbptt=True)
        ds = _seq_data()
        scores = []
        for _ in range(25):
            net.fit(ds, batch_size=16)
            scores.append(float(net.score_))
        assert scores[-1] < scores[0], scores

    def test_tbptt_requires_timestep_labels(self):
        net = _rnn_graph(tbptt=True)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 12, 3)).astype(np.float32)
        y2d = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        with pytest.raises(ValueError, match="per-timestep"):
            net.fit(DataSet(x, y2d), batch_size=4)


class TestCGRnnTimeStep:
    def test_streaming_matches_full_sequence(self):
        """rnnTimeStep over chunks must equal the full-sequence output
        (the reference invariant for stateful stepping)."""
        net = _rnn_graph()
        ds = _seq_data(n=4)
        net.fit(ds, batch_size=4)  # params != init
        full = net.output_single(ds.features)
        net.rnn_clear_previous_state()
        parts = []
        for lo in range(0, 12, 3):
            parts.append(net.rnn_time_step(ds.features[:, lo:lo + 3])[0])
        streamed = np.concatenate(parts, axis=1)
        np.testing.assert_allclose(streamed, full, atol=1e-5)

    def test_single_step_2d_input(self):
        net = _rnn_graph()
        x0 = np.random.default_rng(1).standard_normal((4, 3)).astype(np.float32)
        net.rnn_clear_previous_state()
        y = net.rnn_time_step(x0)[0]
        assert y.shape == (4, 2)

    def test_state_persists_across_calls(self):
        net = _rnn_graph()
        ds = _seq_data(n=2)
        net.rnn_clear_previous_state()
        a1 = net.rnn_time_step(ds.features[:, :6])[0]
        a2 = net.rnn_time_step(ds.features[:, 6:])[0]
        net.rnn_clear_previous_state()
        b2_fresh = net.rnn_time_step(ds.features[:, 6:])[0]
        # second half differs depending on carried state
        assert not np.allclose(a2, b2_fresh)


class TestParallelWrapperFixes:
    def _mln(self, seed=3, tbptt=False):
        b = (
            NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init("xavier")
        )
        lb = b.list()
        if tbptt:
            lb = lb.backprop_type("tbptt", fwd_length=4, back_length=4)
        return MultiLayerNetwork(
            lb.layer(LSTM(n_out=8, activation="tanh") if tbptt else
                     DenseLayer(n_out=8, activation="relu"))
            .layer((RnnOutputLayer if tbptt else OutputLayer)(
                n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 12) if tbptt
                            else InputType.feed_forward(3))
            .build()
        ).init()

    def test_partial_batch_gradient_exact(self):
        """A padded partial batch must produce the SAME update as the
        unpadded batch on a single device (round-1/2 bias eliminated)."""
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        rng = np.random.default_rng(7)
        x = rng.standard_normal((13, 3)).astype(np.float32)  # 13 % 8 != 0
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 13)]
        ds = DataSet(x, y)

        ref = self._mln()
        ref.fit(ds, epochs=1, batch_size=13)
        ref_params = ref.params_flat()

        par = self._mln()
        mesh = TrainingMesh(data=8, devices=jax.devices()[:8])
        pw = ParallelWrapper(par, mesh=mesh)
        pw.fit(ExistingDataSetIterator([ds]), epochs=1)
        np.testing.assert_allclose(par.params_flat(), ref_params,
                                   atol=1e-5, rtol=1e-4)

    def test_tbptt_through_wrapper(self):
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        net = self._mln(tbptt=True)
        mesh = TrainingMesh(data=4, devices=jax.devices()[:4])
        pw = ParallelWrapper(net, mesh=mesh)
        ds = _seq_data(n=8)
        scores = []
        for _ in range(10):
            pw.fit(ExistingDataSetIterator([ds]), epochs=1)
            scores.append(float(net.score_))
        assert scores[-1] < scores[0], scores

    def test_averaging_frequency_warns(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        net = self._mln()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ParallelWrapper.builder(net).averaging_frequency(5)
            assert any("subsumed" in str(x.message) for x in w)


class TestDropoutVariants:
    def _train_with(self, dropout=None, weight_noise=None, seed=4):
        conf = (
            NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="selu", dropout=dropout or 0.0,
                              weight_noise=weight_noise))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((64, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        net.fit(DataSet(x, y), epochs=5, batch_size=32)
        return net, x

    @pytest.mark.parametrize("variant", [
        AlphaDropout(0.2), GaussianDropout(0.3), GaussianNoise(0.2),
    ])
    def test_dropout_variants_train(self, variant):
        net, x = self._train_with(dropout=variant)
        assert np.isfinite(net.score())
        # inference is deterministic (noise train-only)
        np.testing.assert_allclose(net.output(x), net.output(x), atol=0)

    @pytest.mark.parametrize("noise", [
        DropConnect(0.7), WeightNoise(0.05),
    ])
    def test_weight_noise_trains(self, noise):
        net, x = self._train_with(weight_noise=noise)
        assert np.isfinite(net.score())
        np.testing.assert_allclose(net.output(x), net.output(x), atol=0)

    def test_alpha_dropout_preserves_moments(self):
        """AlphaDropout's defining property: output mean/var ≈ input
        mean/var for standard-normal inputs."""
        x = jnp.asarray(np.random.default_rng(0).standard_normal((200, 200)),
                        jnp.float32)
        y = AlphaDropout(0.3).apply(x, jax.random.PRNGKey(1))
        assert abs(float(y.mean())) < 0.05
        assert abs(float(y.std()) - 1.0) < 0.05

    def test_serde_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        conf = (
            NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=4, activation="relu",
                              dropout=GaussianDropout(0.25),
                              weight_noise=DropConnect(0.8)))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3)).build()
        )
        restored = MultiLayerConfiguration.from_json(conf.to_json())
        d = restored.layers[0].dropout
        wn = restored.layers[0].weight_noise
        assert type(d).__name__ == "GaussianDropout" and d.rate == 0.25
        assert type(wn).__name__ == "DropConnect"
        assert wn.weight_retain_prob == 0.8


class TestLegacySolvers:
    def _model_and_data(self, seed=9):
        conf = (
            NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((80, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] + x[:, 1] > 0).astype(int)]
        return net, DataSet(x, y)

    @pytest.mark.parametrize("algo", ["LBFGS", "CONJUGATE_GRADIENT",
                                      "LINE_GRADIENT_DESCENT"])
    def test_full_batch_optimizers_reduce_loss(self, algo):
        from deeplearning4j_tpu.optimize import OptimizationAlgorithm, Solver

        net, ds = self._model_and_data()
        before = net.score(ds)
        solver = (
            Solver.builder().model(net)
            .optimization_algorithm(getattr(OptimizationAlgorithm, algo))
            .max_iterations(40).build()
        )
        final = solver.optimize(ds)
        assert final < before * 0.5, f"{algo}: {before} -> {final}"
        # params written back: model.score agrees
        assert net.score(ds) == pytest.approx(final, rel=1e-4)

    def test_termination_conditions(self):
        """reference optimize/terminations/*: named conditions stop the
        solver early; a huge Norm2 threshold stops after one accepted
        step, EpsTermination stops once improvement stalls."""
        from deeplearning4j_tpu.optimize.solvers import (
            EpsTermination,
            LBFGS,
            Norm2Termination,
            OptimizationAlgorithm,
            Solver,
            ZeroDirection,
        )

        net, ds = self._model_and_data(seed=13)
        opt = LBFGS(max_iterations=40,
                    termination_conditions=[Norm2Termination(1e9)])
        opt.optimize(net, ds)
        # any finite gradient norm < 1e9 => stopped right after step 1
        assert len(opt.score_history) <= 3, opt.score_history

        net2, ds2 = self._model_and_data(seed=13)
        solver = (
            Solver.builder().model(net2)
            .optimization_algorithm(OptimizationAlgorithm.CONJUGATE_GRADIENT)
            .max_iterations(40)
            .termination_conditions(EpsTermination(eps=0.5), ZeroDirection())
            .build()
        )
        final = solver.optimize(ds2)
        # 50% relative-improvement bar triggers long before 40 iterations
        assert len(solver.optimizer.score_history) < 40
        assert np.isfinite(final)

    def test_lbfgs_beats_few_sgd_steps(self):
        """On a small full-batch problem LBFGS should reach a much lower
        loss than the same number of SGD evaluations."""
        from deeplearning4j_tpu.optimize import LBFGS

        net, ds = self._model_and_data(seed=11)
        sgd_net = net.clone()
        for _ in range(40):
            sgd_net.fit(ds, epochs=1, batch_size=80)
        lbfgs_final = LBFGS(max_iterations=40).optimize(net, ds)
        assert lbfgs_final < float(sgd_net.score_)


class TestGradientCompression:
    def test_threshold_encode_decode_roundtrip(self):
        from deeplearning4j_tpu.parallel.compression import (
            threshold_decode,
            threshold_encode,
        )

        g = jnp.asarray([0.5, -0.001, 0.002, -0.8, 0.0, 0.3], jnp.float32)
        msg, residual = threshold_encode(g, jnp.asarray(0.01, jnp.float32), 4)
        assert int(msg.count) == 3  # 0.5, -0.8, 0.3
        dec = threshold_decode(msg, 6)
        # transmitted entries carry ±threshold
        np.testing.assert_allclose(dec[0], 0.01, atol=1e-7)
        np.testing.assert_allclose(dec[3], -0.01, atol=1e-7)
        # residual + decoded == original (nothing lost)
        np.testing.assert_allclose(np.asarray(residual) + np.asarray(dec),
                                   np.asarray(g), atol=1e-6)

    def test_capacity_cap_keeps_largest(self):
        from deeplearning4j_tpu.parallel.compression import threshold_encode

        g = jnp.asarray(np.linspace(0.1, 1.0, 10), jnp.float32)
        msg, _ = threshold_encode(g, jnp.asarray(0.05, jnp.float32), 3)
        sent = sorted(int(i) for i in np.asarray(msg.indices) if i >= 0)
        assert sent == [7, 8, 9]  # three largest magnitudes

    def test_bitmap_roundtrip(self):
        from deeplearning4j_tpu.parallel.compression import (
            bitmap_decode,
            bitmap_encode,
        )

        rng = np.random.default_rng(3)
        g = jnp.asarray(rng.standard_normal(100) * 0.01, jnp.float32)
        t = jnp.asarray(0.005, jnp.float32)
        packed, residual = bitmap_encode(g, t)
        assert packed.dtype == jnp.uint32 and packed.shape == (7,)
        dec = bitmap_decode(packed, t, 100)
        np.testing.assert_allclose(np.asarray(residual) + np.asarray(dec),
                                   np.asarray(g), atol=1e-6)

    def test_residual_accumulates_small_gradients(self):
        """EncodedGradientsAccumulator semantics: sub-threshold gradients
        are delayed, not dropped — repeated small updates eventually
        transmit."""
        from deeplearning4j_tpu.parallel.compression import EncodingHandler

        h = EncodingHandler(size=8, threshold=0.1, capacity=4,
                            adapt_rate=1.0)  # fixed threshold
        g = jnp.asarray([0.04, 0, 0, 0, 0, 0, 0, 0], jnp.float32)
        sent_any = False
        for _ in range(4):
            msg = h.encode_update(g)
            if int(msg.count) > 0:
                sent_any = True
        assert sent_any, "accumulated residual never crossed the threshold"

    def test_compressed_allreduce_approaches_dense_sum(self):
        from deeplearning4j_tpu.parallel.compression import (
            make_compressed_allreduce,
        )
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh

        n, size = 8, 64
        mesh = TrainingMesh(data=n, devices=jax.devices()[:n])
        fn = make_compressed_allreduce(mesh, capacity=64)
        rng = np.random.default_rng(5)
        grads = jnp.asarray(rng.standard_normal((n, size)), jnp.float32)
        residuals = jnp.zeros((n, size), jnp.float32)
        t = jnp.asarray(0.05, jnp.float32)
        # iterate: summed updates + residual carry converge to dense sum
        total = np.zeros((size,), np.float32)
        for _ in range(60):
            summed, residuals = fn(grads * 0.0, residuals, t)  # drain only
            if _ == 0:
                summed0, residuals = fn(grads, residuals, t)
                total += np.asarray(summed0)
            total += np.asarray(summed)
        dense = np.asarray(grads.sum(0))
        # transmitted mass approaches the dense sum within threshold*n slack
        np.testing.assert_allclose(total, dense, atol=0.05 * n + 1e-3)


class TestOutputLayerWeightNoise:
    def test_output_layer_weight_noise_affects_training_loss(self):
        """Weight noise configured on the OUTPUT layer must reach the loss
        path (review regression: the forward stops before the output
        layer, so the score path applies the noise)."""
        import jax as _jax
        from deeplearning4j_tpu.nn.conf.layers import WeightNoise

        def build(noise):
            conf = (
                NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.0))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent", weight_noise=noise))
                .set_input_type(InputType.feed_forward(4)).build()
            )
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(1)
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        ds = DataSet(x, y)
        clean = build(None)
        noisy = build(WeightNoise(0.5))
        clean.fit(ds, epochs=1, batch_size=32)
        noisy.fit(ds, epochs=1, batch_size=32)
        # lr=0 → params unchanged; only the noise can alter the score
        assert float(clean.score_) != float(noisy.score_)


class TestSharedTrainingMaster:
    def _net(self, seed=3, lr=0.1):
        conf = (
            NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build()
        )
        return MultiLayerNetwork(conf).init()

    def _data(self, n=64, seed=0):
        rng = np.random.default_rng(seed)
        centers = rng.standard_normal((3, 5)) * 2
        cls = rng.integers(0, 3, n)
        x = (centers[cls] + rng.standard_normal((n, 5)) * 0.3).astype(np.float32)
        return DataSet(x, np.eye(3, dtype=np.float32)[cls])

    def test_compressed_dp_converges(self):
        from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.shared_training import (
            SharedTrainingMaster,
        )

        # 1-bit updates move each transmitted coordinate by lr*threshold
        # per step — pick a quantum large enough to converge in test time
        # (the reference's adaptive threshold serves the same purpose)
        net = self._net(lr=1.0)
        mesh = TrainingMesh(data=8, devices=jax.devices()[:8])
        master = (SharedTrainingMaster.builder(threshold=0.02)
                  .update_capacity(512).mesh(mesh).build())
        ds = self._data()
        scores = []
        for _ in range(60):
            master.fit(net, ExistingDataSetIterator([ds]), epochs=1)
            scores.append(float(net.score_))
        assert scores[-1] < 0.5 * scores[0], (scores[0], scores[-1])
        assert np.isfinite(master.residual_magnitude())

    def test_compressed_updates_track_exact_dp_direction(self):
        """Per-step updates are sign-quantized (±threshold), so exact
        per-step parity is impossible by design; the contract is that the
        ACCUMULATED compressed update tracks the exact-DP update
        direction (residual carry never loses mass)."""
        from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.shared_training import (
            SharedTrainingMaster,
        )
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        ds = self._data(n=32, seed=5)
        mesh = TrainingMesh(data=8, devices=jax.devices()[:8])

        exact = self._net(seed=9, lr=0.05)
        init = exact.params_flat().copy()
        pw = ParallelWrapper(exact, mesh=mesh)
        comp = self._net(seed=9, lr=0.05)
        master = (SharedTrainingMaster.builder(threshold=0.005)
                  .update_capacity(comp.num_params()).mesh(mesh).build())
        for _ in range(20):
            pw.fit(ExistingDataSetIterator([ds]), epochs=1)
            master.fit(comp, ExistingDataSetIterator([ds]), epochs=1)
        d_exact = exact.params_flat() - init
        d_comp = comp.params_flat() - init
        cos = float(d_exact @ d_comp /
                    (np.linalg.norm(d_exact) * np.linalg.norm(d_comp) + 1e-12))
        assert cos > 0.7, f"update-direction cosine {cos:.3f}"
