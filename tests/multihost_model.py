"""Shared model/data definitions for the multi-host parity test — imported
by both the worker processes and the single-process reference run so both
sides train the identical net on identical global batches."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.updaters import Sgd

GLOBAL_BATCH = 16
N_BATCHES = 4


def build_net() -> MultiLayerNetwork:
    """LeNet-style CNN (the parity test model; reference
    ``TestCompareParameterAveragingSparkVsSingleMachine.java`` uses a
    small deterministic net the same way). Plain SGD so the update is
    bit-for-bit linear in the averaged gradient."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .updater(Sgd(0.1))
        .weight_init("xavier")
        .list()
        .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5), activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional(16, 16, 1))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def global_batches() -> ListDataSetIterator:
    """Deterministic synthetic MNIST-shaped stream; EVERY process
    constructs the identical global batches (the ShardedDataSetIterator
    contract)."""
    rng = np.random.default_rng(777)
    n = GLOBAL_BATCH * N_BATCHES
    x = rng.standard_normal((n, 16, 16, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    return ListDataSetIterator(DataSet(x, y), GLOBAL_BATCH)
