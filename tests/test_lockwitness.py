"""Lock-witness tests (obs/lockwitness.py): the synthetic ABBA drill
(typed LockOrderViolationError + lock_cycle flight event), witness
semantics (reentrancy, same-class, observe mode, passthrough), and the
chaos-drill integration (scorecard lock_cycles)."""

import threading
import time

import pytest

from deeplearning4j_tpu.obs import flight, lockwitness as lw
from deeplearning4j_tpu.obs.lockwitness import (
    LockOrderViolationError,
    witnessed_lock,
    witnessed_rlock,
)


@pytest.fixture(autouse=True)
def _clean_witness():
    lw.reset()
    yield
    lw.reset()


def _abba(strict=True):
    """Two threads acquire two locks in opposite orders, barrier-synced
    so both orderings are recorded; returns the violations raised."""
    A = witnessed_rlock("abba.A")
    B = witnessed_rlock("abba.B")
    errors = []
    barrier = threading.Barrier(2)

    def forward():
        with A:
            barrier.wait()
            time.sleep(0.05)
            try:
                with B:
                    pass
            except LockOrderViolationError as e:
                errors.append(e)

    def backward():
        barrier.wait()
        with B:
            time.sleep(0.05)
            try:
                with A:
                    pass
            except LockOrderViolationError as e:
                errors.append(e)

    with lw.armed(strict=strict):
        # daemon: under observe-mode arming an ABBA genuinely
        # deadlocks (nothing raises to break it) — live threads must
        # never block interpreter exit
        ts = [threading.Thread(target=forward, daemon=True),
              threading.Thread(target=backward, daemon=True)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    return errors


class TestSyntheticABBA:
    def test_abba_raises_typed_with_cycle_and_flight_event(self):
        seq0 = flight.default_flight_recorder().recorded_total
        errors = _abba(strict=True)
        # exactly one side closes the cycle (the second ordering seen)
        assert len(errors) == 1
        e = errors[0]
        assert isinstance(e, LockOrderViolationError)
        assert isinstance(e, RuntimeError)  # typed taxonomy, not a hang
        assert set(e.cycle) == {"abba.A", "abba.B"}
        cyc = lw.cycles()
        assert len(cyc) == 1 and cyc[0]["strict"] is True
        evs = [ev for ev in flight.default_flight_recorder().events()
               if ev["seq"] >= seq0 and ev["kind"] == "lock_cycle"]
        assert len(evs) == 1
        assert "abba.A" in evs[0]["cycle"] and "abba.B" in evs[0]["cycle"]

    def test_observe_mode_records_without_raising(self):
        # single-threaded inversion: under observe arming a real
        # two-thread ABBA would genuinely deadlock (nothing raises to
        # break it) — which is exactly why the drill matrix pairs
        # observe mode with drill deadlines
        A = lw.witnessed_rlock("obs.A")
        B = lw.witnessed_rlock("obs.B")
        with lw.armed(strict=False):
            with A:
                with B:
                    pass
            with B:
                with A:
                    pass
        assert len(lw.cycles()) == 1
        assert lw.cycles()[0]["strict"] is False

    def test_cycle_reported_once_not_per_acquire(self):
        A = witnessed_rlock("once.A")
        B = witnessed_rlock("once.B")
        with lw.armed(strict=False):
            with A:
                with B:
                    pass
            for _ in range(5):
                with B:
                    with A:
                        pass
        assert len(lw.cycles()) == 1


class TestWitnessSemantics:
    def test_reentrant_rlock_records_no_edges(self):
        A = witnessed_rlock("re.A")
        with lw.armed():
            with A:
                with A:
                    pass
        assert lw.edges() == {}

    def test_consistent_order_passes_and_builds_graph(self):
        A = witnessed_rlock("ord.A")
        B = witnessed_rlock("ord.B")
        C = witnessed_rlock("ord.C")
        with lw.armed():
            with A:
                with B:
                    with C:
                        pass
            with A:
                with C:
                    pass
        assert lw.cycles() == []
        assert "ord.B" in lw.edges()["ord.A"]
        assert "ord.C" in lw.edges()["ord.B"]

    def test_transitive_cycle_detected(self):
        # A->B and B->C recorded, then C->A closes a 3-cycle
        A = witnessed_rlock("tri.A")
        B = witnessed_rlock("tri.B")
        C = witnessed_rlock("tri.C")
        with lw.armed(strict=True):
            with A:
                with B:
                    pass
            with B:
                with C:
                    pass
            with pytest.raises(LockOrderViolationError) as ei:
                with C:
                    with A:
                        pass
            assert ei.value.cycle[0] == "tri.A"
            assert ei.value.cycle[-1] == "tri.A"

    def test_same_order_class_instances_skip(self):
        # two instances sharing a class: indistinguishable from
        # reentrancy at class granularity — documented skip
        a1 = witnessed_rlock("mm.lock")
        a2 = witnessed_rlock("mm.lock")
        with lw.armed():
            with a1:
                with a2:
                    pass
        assert lw.cycles() == []

    def test_unarmed_is_passthrough(self):
        A = witnessed_rlock("pt.A")
        B = witnessed_rlock("pt.B")
        with A:
            with B:
                pass
        with B:
            with A:
                pass
        assert lw.edges() == {} and lw.cycles() == []

    def test_plain_lock_is_not_reentrant(self):
        lk = witnessed_lock("plain")
        assert lk.acquire(blocking=False)
        assert not lk.acquire(blocking=False)
        lk.release()

    def test_release_after_disarm_leaves_no_phantom_held(self):
        """Review regression: a lock acquired while armed but released
        after disarm must not leave a phantom held entry fabricating
        edges (and false cycles) in every later armed run."""
        A = witnessed_rlock("ph.A")
        B = witnessed_rlock("ph.B")
        A.acquire()
        with lw.armed():
            pass  # disarmed while A is (unarmed-)held: nothing pushed
        A.release()
        arm_a = witnessed_rlock("ph.armA")
        with lw.armed():
            arm_a.acquire()
        arm_a.release()  # released AFTER disarm: must still pop
        with lw.armed():
            with B:
                pass
        assert lw.edges() == {}  # no phantom ph.A/ph.armA -> ph.B edge
        assert lw.cycles() == []

    def test_nested_arming_depth(self):
        A = witnessed_rlock("nest.A")
        with lw.armed():
            with lw.armed():
                pass
            # still armed after the inner block exits
            with A:
                pass
        assert lw.armed_() is False


class TestChaosIntegration:
    def test_drill_scorecard_reports_zero_lock_cycles(self):
        from deeplearning4j_tpu.chaos import drills

        card = drills.run_matrix(names=["checkpoint_enospc"])
        assert card["ok"], card
        assert card["lock_cycles"] == 0
        checks = {c["name"]: c["ok"]
                  for c in card["drills"][0]["checks"]}
        assert checks.get("no_lock_cycles") is True

    def test_injected_inversion_fails_the_drill_invariant(self):
        """A drill whose workload contains an ABBA inversion goes RED
        on the no_lock_cycles invariant — without crashing the drill
        (observe-mode arming)."""
        from deeplearning4j_tpu.chaos import drills

        X = witnessed_rlock("drillbad.X")
        Y = witnessed_rlock("drillbad.Y")

        def bad(ctx):
            with X:
                with Y:
                    pass
            with Y:
                with X:
                    pass

        name = "_test_lock_inversion"
        drills.DRILLS[name] = drills.Drill(
            name, bad, "test", [], paired=False, fast=True,
            deadline_s=30.0, description="synthetic inversion")
        try:
            r = drills.run_drill(name)
            assert not r.ok
            failed = [c for c in r.checks if not c["ok"]]
            assert [c["name"] for c in failed] == ["no_lock_cycles"]
            assert "drillbad" in failed[0]["detail"]
        finally:
            drills.DRILLS.pop(name, None)
