"""Gradient checks — analytic (autodiff) vs numerical in fp64.

Modeled on the reference backbone suites
``gradientcheck/GradientCheckTestsComputationGraph.java`` /
``CNNGradientCheckTest.java`` (SURVEY.md §4.1). Tiny nets, fp64.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    ElementWiseMultiplicationLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SimpleRnn,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.gradient_check import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.updaters import Sgd


def _data(n=4, n_in=3, n_classes=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[rng.integers(0, n_classes, n)]
    return DataSet(x, y)


def _build(layers, input_type, l1=0.0, l2=0.0):
    b = NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).weight_init("xavier")
    if l1:
        b = b.l1(l1)
    if l2:
        b = b.l2(l2)
    lb = b.list()
    for l in layers:
        lb = lb.layer(l)
    return MultiLayerNetwork(lb.set_input_type(input_type).build()).init()


class TestGradientChecks:
    def test_mlp_mcxent(self):
        net = _build(
            [DenseLayer(n_out=5, activation="tanh"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.feed_forward(3),
        )
        assert check_gradients(net, _data(), print_results=True)

    def test_mlp_mse_identity(self):
        rng = np.random.default_rng(1)
        ds = DataSet(rng.standard_normal((4, 3)).astype(np.float32),
                     rng.standard_normal((4, 2)).astype(np.float32))
        net = _build(
            [DenseLayer(n_out=4, activation="sigmoid"),
             OutputLayer(n_out=2, activation="identity", loss="mse")],
            InputType.feed_forward(3),
        )
        assert check_gradients(net, ds, print_results=True)

    def test_mlp_with_l1_l2(self):
        net = _build(
            [DenseLayer(n_out=4, activation="relu"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.feed_forward(3), l1=0.01, l2=0.02,
        )
        assert check_gradients(net, _data(seed=3), print_results=True)

    def test_elementwise_mult(self):
        net = _build(
            [DenseLayer(n_out=4, activation="tanh"),
             ElementWiseMultiplicationLayer(activation="identity"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.feed_forward(3),
        )
        assert check_gradients(net, _data(), print_results=True)

    def test_cnn(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 6, 6, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        net = _build(
            [ConvolutionLayer(n_out=2, kernel_size=3, activation="tanh"),
             SubsamplingLayer(kernel_size=2, stride=2, pooling_type="max"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(6, 6, 1),
        )
        assert check_gradients(net, DataSet(x, y), print_results=True)

    def test_cnn_avgpool_batchnorm(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 6, 6, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        net = _build(
            [ConvolutionLayer(n_out=2, kernel_size=3, activation="identity"),
             BatchNormalization(),
             SubsamplingLayer(kernel_size=2, stride=2, pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(6, 6, 1),
        )
        assert check_gradients(net, DataSet(x, y), print_results=True)

    def test_lstm_global_pool(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 5, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        net = _build(
            [LSTM(n_out=3),
             GlobalPoolingLayer(pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.recurrent(2, 5),
        )
        assert check_gradients(net, DataSet(x, y), print_results=True)

    def test_graves_lstm_rnn_output(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 4))]
        net = _build(
            [GravesLSTM(n_out=3),
             RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.recurrent(2, 4),
        )
        assert check_gradients(net, DataSet(x, y), print_results=True)

    def test_simple_rnn_masked(self):
        rng = np.random.default_rng(0)
        n, t = 3, 5
        x = rng.standard_normal((n, t, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (n, t))]
        mask = (np.arange(t)[None, :] < rng.integers(2, t + 1, n)[:, None]).astype(np.float32)
        net = _build(
            [SimpleRnn(n_out=3),
             RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.recurrent(2, t),
        )
        assert check_gradients(net, DataSet(x, y, features_mask=mask, labels_mask=mask),
                               print_results=True)

    @pytest.mark.parametrize("loss,act", [
        ("xent", "sigmoid"),
        ("l2", "identity"),
        ("mae", "identity"),
        ("kl_divergence", "softmax"),
        ("poisson", "softplus"),
        ("squared_hinge", "identity"),
    ])
    def test_loss_functions(self, loss, act):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 3)).astype(np.float32)
        if loss in ("xent", "kl_divergence"):
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        elif loss == "poisson":
            y = rng.poisson(2.0, (4, 2)).astype(np.float32)
        elif loss == "squared_hinge":
            y = (2 * rng.integers(0, 2, (4, 2)) - 1).astype(np.float32)
        else:
            y = rng.standard_normal((4, 2)).astype(np.float32)
        net = _build(
            [DenseLayer(n_out=4, activation="tanh"),
             OutputLayer(n_out=2, activation=act, loss=loss)],
            InputType.feed_forward(3),
        )
        assert check_gradients(net, DataSet(x, y), print_results=True), f"{loss}/{act}"


class TestMoEGradients:
    def test_moe_layer_gradcheck(self):
        """fp64 central-difference check through the dense-dispatch MoE
        (router, experts, and the Switch aux loss all differentiable at a
        generic point; the top-k selection is piecewise-constant)."""
        from deeplearning4j_tpu.nn.conf.layers import MixtureOfExpertsLayer

        net = _build(
            [DenseLayer(n_out=6, activation="tanh"),
             MixtureOfExpertsLayer(n_experts=3, top_k=2, capacity_factor=2.0,
                                   hidden_ratio=2, aux_loss_weight=0.05),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.feed_forward(3),
        )
        assert check_gradients(net, _data(seed=11), print_results=True)

    def test_moe_transformer_block_graph_gradcheck(self):
        """CG fp64 check through MoETransformerBlock (attention + router +
        experts + aux loss in one block)."""
        from deeplearning4j_tpu.nn.conf.layers import (
            MoETransformerBlock, PositionalEmbeddingLayer, RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.gradient_check import check_gradients_graph
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (
            NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(6, 4))
            .add_layer("pos", PositionalEmbeddingLayer(), "in")
            .add_layer("moe", MoETransformerBlock(n_heads=2, n_experts=3,
                                                  capacity_factor=2.0,
                                                  aux_loss_weight=0.05),
                       "pos")
            .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "moe")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(3)
        x = rng.standard_normal((3, 4, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (3, 4))]
        assert check_gradients_graph(net, DataSet(x, y), print_results=True)


class TestGradientChecksExtended:
    """Widened layer sweep mirroring the reference's CNNGradientCheckTest
    special-conv cases and attention/VAE additions."""

    def test_deconvolution(self):
        from deeplearning4j_tpu.nn.conf.layers import Deconvolution2D

        net = _build(
            [ConvolutionLayer(n_out=3, kernel_size=(3, 3), stride=(2, 2)),
             Deconvolution2D(n_out=2, kernel_size=(3, 3), stride=(2, 2)),
             GlobalPoolingLayer(pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(8, 8, 2),
        )
        rng = np.random.default_rng(2)
        x = rng.standard_normal((3, 8, 8, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        assert check_gradients(net, DataSet(x, y))

    def test_separable_conv_upsampling(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            SeparableConvolution2D,
            Upsampling2D,
        )

        net = _build(
            [SeparableConvolution2D(n_out=4, kernel_size=(3, 3), depth_multiplier=2),
             Upsampling2D(size=2),
             GlobalPoolingLayer(pooling_type="max"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(6, 6, 2),
        )
        rng = np.random.default_rng(3)
        x = rng.standard_normal((3, 6, 6, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        assert check_gradients(net, DataSet(x, y))

    def test_crop_pad_space_to_depth(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            Cropping2D,
            SpaceToDepthLayer,
            ZeroPaddingLayer,
        )

        net = _build(
            [ZeroPaddingLayer(pad=(1, 1)),
             Cropping2D(crop=(1, 1)),
             SpaceToDepthLayer(block_size=2),
             GlobalPoolingLayer(pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(4, 4, 2),
        )
        rng = np.random.default_rng(4)
        x = rng.standard_normal((3, 4, 4, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        assert check_gradients(net, DataSet(x, y))

    def test_embedding_sequence_lstm(self):
        from deeplearning4j_tpu.nn.conf.layers import EmbeddingSequenceLayer

        net = _build(
            [EmbeddingSequenceLayer(n_in=7, n_out=4),
             LSTM(n_out=5),
             RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
            InputType.recurrent(1),
        )
        rng = np.random.default_rng(5)
        x = rng.integers(0, 7, (2, 5, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 5))]
        assert check_gradients(net, DataSet(x, y))

    def test_self_attention_block(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            SelfAttentionLayer,
        )

        net = _build(
            [SelfAttentionLayer(n_heads=2, causal=True),
             RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.recurrent(6),
        )
        rng = np.random.default_rng(6)
        x = rng.standard_normal((2, 4, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 4))]
        assert check_gradients(net, DataSet(x, y))

    def test_vae_supervised(self):
        from deeplearning4j_tpu.nn.conf.layers.variational import (
            VariationalAutoencoder,
        )

        net = _build(
            [VariationalAutoencoder(n_out=3, encoder_layer_sizes=(6,),
                                    decoder_layer_sizes=(6,)),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.feed_forward(4),
        )
        rng = np.random.default_rng(7)
        x = rng.standard_normal((3, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        assert check_gradients(net, DataSet(x, y))

    def test_deconv_matches_gradient_of_conv(self):
        """Deconvolution2D forward == jax.vjp of the forward conv (the
        TF/Keras Conv2DTranspose convention) for p=0 and p=1 — guards
        the transpose_kernel/padding translation in conv.py (regression:
        the layer once double-swapped I/O and shape-errored for
        n_in != n_out)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from deeplearning4j_tpu.nn.conf.layers import Deconvolution2D
        from deeplearning4j_tpu.nn.conf.input_type import InputType

        rng = np.random.default_rng(0)
        for p in (0, 1):
            layer = Deconvolution2D(n_out=2, kernel_size=(3, 3),
                                    stride=(2, 2), padding=(p, p),
                                    activation="identity", has_bias=False)
            it = InputType.convolutional(5, 5, 3)
            layer.initialize(it)
            params = layer.init_params(jax.random.PRNGKey(0), it)
            x = jnp.asarray(rng.standard_normal((2, 5, 5, 3)).astype(np.float32))
            y, _ = layer.apply(params, x)
            H = 2 * 4 + 3 - 2 * p
            fwd = lambda inp: lax.conv_general_dilated(
                inp, params["W"], (2, 2), [(p, p), (p, p)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            _, vjp = jax.vjp(fwd, jnp.zeros((2, H, H, 2)))
            ref = vjp(x)[0]
            assert y.shape == ref.shape == (2, H, H, 2)
            np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                       atol=1e-5)

    def test_conv1d_pipeline(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            Convolution1DLayer,
            Subsampling1DLayer,
            Upsampling1D,
        )

        net = _build(
            [Convolution1DLayer(n_out=4, kernel_size=3),
             Subsampling1DLayer(kernel_size=2, stride=2),
             Upsampling1D(size=2),
             RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.recurrent(3),
        )
        rng = np.random.default_rng(8)
        x = rng.standard_normal((2, 8, 3)).astype(np.float32)
        # output time length after conv1d(k=3)/pool(2)/up(2)
        T_out = net.output(x).shape[1]
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, T_out))]
        assert check_gradients(net, DataSet(x, y))

    def test_local_response_normalization(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            LocalResponseNormalization,
        )

        net = _build(
            [ConvolutionLayer(n_out=4, kernel_size=(3, 3)),
             LocalResponseNormalization(),
             GlobalPoolingLayer(pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
            InputType.convolutional(6, 6, 2),
        )
        rng = np.random.default_rng(9)
        x = rng.standard_normal((3, 6, 6, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
        assert check_gradients(net, DataSet(x, y))
