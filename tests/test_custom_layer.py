"""User-defined custom layers — the SameDiff-layer-bridge equivalent
(reference ``nn/conf/layers/samediff/AbstractSameDiffLayer.java`` +
``nn/layers/samediff/SameDiffLayer.java``: users write a layer against
the autodiff API and it participates in networks, serde and training).

Here the story is simpler and fully supported: subclass ``Layer`` (or
``FeedForwardLayer``), write ``init_params`` + ``apply`` in jax.numpy —
autodiff and jit come for free — and ``@serde.register`` makes it
JSON/checkpoint round-trippable. This test IS the documented recipe.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration, serde
from deeplearning4j_tpu.nn.conf.layers import OutputLayer
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.nn.gradient_check import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.model_serializer import ModelSerializer
from deeplearning4j_tpu.updaters import Adam


# ---- the recipe: a custom gated-linear layer in ~20 lines ----------------
@serde.register
class GatedLinearLayer(FeedForwardLayer):
    """y = (x @ W) * sigmoid(x @ G) — a user-defined layer. Everything a
    built-in layer can do (autodiff, jit, serde, checkpoints, gradient
    checking) works without further registration."""

    def init_params(self, rng, input_type, dtype=jnp.float32):
        assert self.n_in and self.n_out
        k1, k2 = jax.random.split(rng)
        return {
            "W": self._draw_weight(k1, (self.n_in, self.n_out),
                                   self.n_in, self.n_out, dtype),
            "G": self._draw_weight(k2, (self.n_in, self.n_out),
                                   self.n_in, self.n_out, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y = (x @ params["W"]) * jax.nn.sigmoid(x @ params["G"]) + params["b"]
        return y, state or {}


def _net(seed=3):
    conf = (
        NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.02))
        .weight_init("xavier").list()
        .layer(GatedLinearLayer(n_out=12))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(5)).build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] * x[:, 1] > 0).astype(int)]
    return DataSet(x, y)


class TestCustomLayer:
    def test_trains(self):
        net = _net()
        ds = _data()
        scores = []
        for _ in range(20):
            net.fit(ds, epochs=1, batch_size=32)
            scores.append(float(net.score_))
        assert scores[-1] < scores[0]

    def test_gradient_check(self):
        """The fp64 central-difference checker works on user layers
        unchanged (the reference's custom-layer suites do the same,
        ``nn/layers/samediff/testlayers/``)."""
        net = _net()
        assert check_gradients(net, _data(n=6), print_results=False)

    def test_json_serde_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        net = _net()
        restored = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert isinstance(restored.layers[0], GatedLinearLayer)
        assert restored.layers[0].n_out == 12

    def test_checkpoint_roundtrip(self, tmp_path):
        net = _net()
        ds = _data()
        net.fit(ds, epochs=2, batch_size=32)
        p = str(tmp_path / "custom.zip")
        ModelSerializer.write_model(net, p)
        net2 = ModelSerializer.restore_multi_layer_network(p)
        np.testing.assert_allclose(net.output(ds.features),
                                   net2.output(ds.features), atol=1e-6)
