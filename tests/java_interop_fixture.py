"""Builders for Java-stack-layout model-zip fixtures.

Constructs zips byte-for-byte in the Java ``ModelSerializer.writeModel``
layout (``util/ModelSerializer.java:39-135``): Jackson-schema
``configuration.json`` (WRAPPER_OBJECT layer names, ``@class``
activations/losses/updaters) + ``coefficients.bin`` as an ``Nd4j.write``
stream of the flattened param row-vector in each ParamInitializer's view
order. There is no JVM in this environment, so the fixtures are
hand-authored to the format contract documented in
``deeplearning4j_tpu/modelimport/dl4j/loader.py`` — the committed-zip
gate test (RegressionTest080-style) then locks loader behavior against
them, and the numpy-forward oracle validates the de-flattening
independently of the loader.

All params come from a seeded RNG so tests can regenerate the exact
arrays and compute expected outputs with plain numpy.
"""

import io
import json
import zipfile

import numpy as np

from deeplearning4j_tpu.modelimport.dl4j import nd4j_bin

ACT = "org.nd4j.linalg.activations.impl."
LOSS = "org.nd4j.linalg.lossfunctions.impl."
UPD = "org.nd4j.linalg.learning.config."


def _zip_bytes(conf: dict, flat: np.ndarray) -> bytes:
    buf = io.BytesIO()
    nd4j_bin.write_array(buf, flat.reshape(1, -1).astype(np.float32))
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", json.dumps(conf, indent=2))
        z.writestr("coefficients.bin", buf.getvalue())
    return out.getvalue()


def mlp_params(seed=1234):
    """Arrays in Java shapes for dense(4->8 relu) + output(8->3 softmax)."""
    r = np.random.default_rng(seed)
    return {
        "w0": r.normal(0, 0.4, (4, 8)).astype(np.float32),
        "b0": r.normal(0, 0.1, (8,)).astype(np.float32),
        "w1": r.normal(0, 0.4, (8, 3)).astype(np.float32),
        "b1": r.normal(0, 0.1, (3,)).astype(np.float32),
    }


def mlp_zip_bytes(seed=1234) -> bytes:
    p = mlp_params(seed)
    conf = {
        "backprop": True, "backpropType": "Standard", "pretrain": False,
        "tbpttFwdLength": 20, "tbpttBackLength": 20,
        "confs": [
            {"seed": 42, "miniBatch": True, "minimize": True,
             "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
             "layer": {"dense": {
                 "nIn": 4, "nOut": 8,
                 "activationFn": {"@class": ACT + "ActivationReLU"},
                 "weightInit": "XAVIER", "biasInit": 0.0,
                 "l1": 0.0, "l2": 0.0, "l1Bias": 0.0, "l2Bias": 0.0,
                 "iUpdater": {"@class": UPD + "Adam",
                              "learningRate": 0.005, "beta1": 0.9,
                              "beta2": 0.999, "epsilon": 1e-8}}}},
            {"seed": 42, "miniBatch": True, "minimize": True,
             "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
             "layer": {"output": {
                 "nIn": 8, "nOut": 3,
                 "activationFn": {"@class": ACT + "ActivationSoftmax"},
                 "lossFn": {"@class": LOSS + "LossMCXENT"},
                 "weightInit": "XAVIER", "biasInit": 0.0,
                 "l1": 0.0, "l2": 0.0, "l1Bias": 0.0, "l2Bias": 0.0,
                 "iUpdater": {"@class": UPD + "Adam",
                              "learningRate": 0.005, "beta1": 0.9,
                              "beta2": 0.999, "epsilon": 1e-8}}}},
        ],
    }
    # DefaultParamInitializer layout: W ('f' of (nIn,nOut)) then b
    flat = np.concatenate([
        p["w0"].reshape(-1, order="F"), p["b0"],
        p["w1"].reshape(-1, order="F"), p["b1"],
    ])
    return _zip_bytes(conf, flat)


def mlp_nobias_zip_bytes(seed=1234) -> bytes:
    """Same MLP but the dense layer has ``hasBias: false`` — its
    coefficients.bin holds only W, so a loader that unconditionally
    consumes a bias mis-slices every parameter after it."""
    p = mlp_params(seed)
    conf = json.loads(
        zipfile.ZipFile(io.BytesIO(mlp_zip_bytes(seed))).read(
            "configuration.json"))
    conf["confs"][0]["layer"]["dense"]["hasBias"] = False
    flat = np.concatenate([
        p["w0"].reshape(-1, order="F"),
        p["w1"].reshape(-1, order="F"), p["b1"],
    ])
    return _zip_bytes(conf, flat)


def mlp_nobias_forward_numpy(p, x):
    h = np.maximum(x @ p["w0"], 0.0)
    z = h @ p["w1"] + p["b1"]
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def mlp_forward_numpy(p, x):
    h = np.maximum(x @ p["w0"] + p["b0"], 0.0)
    z = h @ p["w1"] + p["b1"]
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def cnn_params(seed=77):
    """conv(1->3, 3x3) OIHW + BN(3) + dense(48->5 softmax output);
    input 6x6x1 image."""
    r = np.random.default_rng(seed)
    return {
        "convW": r.normal(0, 0.3, (3, 1, 3, 3)).astype(np.float32),  # OIHW
        "convB": r.normal(0, 0.1, (3,)).astype(np.float32),
        "gamma": (1.0 + 0.1 * r.normal(size=3)).astype(np.float32),
        "beta": (0.1 * r.normal(size=3)).astype(np.float32),
        "mean": (0.05 * r.normal(size=3)).astype(np.float32),
        "var": (1.0 + 0.1 * np.abs(r.normal(size=3))).astype(np.float32),
        "wOut": r.normal(0, 0.3, (12, 5)).astype(np.float32),
        "bOut": r.normal(0, 0.1, (5,)).astype(np.float32),
    }


def cnn_zip_bytes(seed=77) -> bytes:
    p = cnn_params(seed)
    common = {"seed": 7, "miniBatch": True, "minimize": True,
              "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT"}
    upd = {"@class": UPD + "Nesterovs", "learningRate": 0.01,
           "momentum": 0.9}
    conf = {
        "backprop": True, "backpropType": "Standard", "pretrain": False,
        "tbpttFwdLength": 20, "tbpttBackLength": 20,
        "confs": [
            {**common, "layer": {"convolution": {
                "nIn": 1, "nOut": 3, "kernelSize": [3, 3],
                "stride": [1, 1], "padding": [0, 0],
                "convolutionMode": "Truncate", "hasBias": True,
                "activationFn": {"@class": ACT + "ActivationIdentity"},
                "weightInit": "XAVIER", "iUpdater": upd}}},
            {**common, "layer": {"batchNormalization": {
                "nIn": 3, "nOut": 3, "decay": 0.9, "eps": 1e-5,
                "gamma": 1.0, "beta": 0.0, "lockGammaBeta": False,
                "iUpdater": upd}}},
            # Java BN does NOT apply its activationFn (nn/layers/
            # normalization/BatchNormalization.java:225-226 activate() is
            # just preOutput) — an explicit activation layer follows
            {**common, "layer": {"activation": {
                "activationFn": {"@class": ACT + "ActivationReLU"}}}},
            {**common, "layer": {"subsampling": {
                "poolingType": "MAX", "kernelSize": [2, 2],
                "stride": [2, 2], "padding": [0, 0],
                "convolutionMode": "Truncate"}}},
            {**common, "layer": {"output": {
                "nIn": 12, "nOut": 5,
                "activationFn": {"@class": ACT + "ActivationSoftmax"},
                "lossFn": {"@class": LOSS + "LossMCXENT"},
                "weightInit": "XAVIER", "iUpdater": upd}}},
        ],
        "inputPreProcessors": {
            "4": {"cnnToFeedForward": {
                "inputHeight": 2, "inputWidth": 2, "numChannels": 3}},
        },
    }
    # Conv layout: bias FIRST then 'c'-order OIHW W
    # (ConvolutionParamInitializer.java:105-132); BN: gamma,beta,mean,var
    flat = np.concatenate([
        p["convB"], p["convW"].reshape(-1, order="C"),
        p["gamma"], p["beta"], p["mean"], p["var"],
        p["wOut"].reshape(-1, order="F"), p["bOut"],
    ])
    return _zip_bytes(conf, flat)


def cnn_forward_numpy(p, x_nhwc):
    """Plain-numpy oracle: conv valid 3x3 -> BN(inference) -> relu ->
    maxpool 2x2 -> flatten (Java NCHW flatten order) -> softmax dense."""
    b, h, w, _ = x_nhwc.shape
    oh, ow = h - 2, w - 2
    conv = np.zeros((b, oh, ow, 3), np.float32)
    for o in range(3):
        acc = np.zeros((b, oh, ow), np.float32)
        for kh in range(3):
            for kw in range(3):
                acc += p["convW"][o, 0, kh, kw] * \
                    x_nhwc[:, kh:kh + oh, kw:kw + ow, 0]
        conv[..., o] = acc + p["convB"][o]
    bn = (conv - p["mean"]) / np.sqrt(p["var"] + 1e-5) * p["gamma"] \
        + p["beta"]
    act = np.maximum(bn, 0.0)
    pool = np.max(
        act.reshape(b, oh // 2, 2, ow // 2, 2, 3), axis=(2, 4))
    # Java CnnToFeedForwardPreProcessor flattens NCHW: channel-major
    flatv = np.transpose(pool, (0, 3, 1, 2)).reshape(b, -1)
    z = flatv @ p["wOut"] + p["bOut"]
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def lstm_params(seed=9):
    r = np.random.default_rng(seed)
    return {
        "Wx": r.normal(0, 0.3, (5, 24)).astype(np.float32),
        "Wh": r.normal(0, 0.3, (6, 24)).astype(np.float32),
        "b": r.normal(0, 0.1, (24,)).astype(np.float32),
        "wOut": r.normal(0, 0.3, (6, 2)).astype(np.float32),
        "bOut": r.normal(0, 0.1, (2,)).astype(np.float32),
    }


def lstm_zip_bytes(seed=9) -> bytes:
    p = lstm_params(seed)
    common = {"seed": 3, "miniBatch": True, "minimize": True,
              "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT"}
    upd = {"@class": UPD + "Sgd", "learningRate": 0.05}
    conf = {
        "backprop": True, "backpropType": "Standard", "pretrain": False,
        "tbpttFwdLength": 20, "tbpttBackLength": 20,
        "confs": [
            {**common, "layer": {"LSTM": {
                "nIn": 5, "nOut": 6, "forgetGateBiasInit": 1.0,
                "activationFn": {"@class": ACT + "ActivationTanH"},
                "gateActivationFn": {"@class": ACT + "ActivationSigmoid"},
                "weightInit": "XAVIER", "iUpdater": upd}}},
            {**common, "layer": {"rnnoutput": {
                "nIn": 6, "nOut": 2,
                "activationFn": {"@class": ACT + "ActivationSoftmax"},
                "lossFn": {"@class": LOSS + "LossMCXENT"},
                "weightInit": "XAVIER", "iUpdater": upd}}},
        ],
    }
    # LSTMParamInitializer layout: W ('f'), RW ('f'), b; IFOG columns
    flat = np.concatenate([
        p["Wx"].reshape(-1, order="F"), p["Wh"].reshape(-1, order="F"),
        p["b"],
        p["wOut"].reshape(-1, order="F"), p["bOut"],
    ])
    return _zip_bytes(conf, flat)


def lstm_forward_numpy(p, x_btf):
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    b, t, _ = x_btf.shape
    n = 6
    h = np.zeros((b, n), np.float32)
    c = np.zeros((b, n), np.float32)
    hs = []
    for step in range(t):
        z = x_btf[:, step] @ p["Wx"] + h @ p["Wh"] + p["b"]
        i = sig(z[:, :n])
        f = sig(z[:, n:2 * n])
        o = sig(z[:, 2 * n:3 * n])
        g = np.tanh(z[:, 3 * n:])
        c = f * c + i * g
        h = o * np.tanh(c)
        hs.append(h)
    hseq = np.stack(hs, axis=1)  # (b, t, n)
    zz = hseq @ p["wOut"] + p["bOut"]
    e = np.exp(zz - zz.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


FIXTURES = {
    "java_mlp.zip": mlp_zip_bytes,
    "java_cnn.zip": cnn_zip_bytes,
    "java_lstm.zip": lstm_zip_bytes,
}


def write_fixtures(directory):
    import os

    os.makedirs(directory, exist_ok=True)
    for name, fn in FIXTURES.items():
        with open(os.path.join(directory, name), "wb") as f:
            f.write(fn())


if __name__ == "__main__":
    import os
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "fixtures", "java_interop")
    write_fixtures(out)
    print("wrote", sorted(FIXTURES), "to", out)
