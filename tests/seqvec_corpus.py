"""Shared synthetic corpus for the distributed-embedding parity test:
two disjoint topics whose words co-occur only within their topic, so any
correct word2vec run puts in-topic similarity far above cross-topic.
Deterministic — every process builds the identical vocab + sequences
(the reference TextPipeline's broadcast-vocabulary invariant)."""

import numpy as np

from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabWord

TOPIC_A = list(range(0, 8))    # word ids 0..7
TOPIC_B = list(range(8, 16))   # word ids 8..15
N_SENT = 240
SENT_LEN = 12


def build_corpus_and_vocab():
    rng = np.random.default_rng(1337)
    seqs = []
    for i in range(N_SENT):
        # period-4 topic pattern: round-robin sharding (i % nprocs) still
        # hands every process a balanced mix of both topics
        topic = TOPIC_A if (i % 4) < 2 else TOPIC_B
        seqs.append(rng.choice(topic, SENT_LEN).astype(np.int32))
    vocab = AbstractCache()
    # strictly-descending fake counts pin update_indices' frequency sort
    # to identity, so vocab index i == sequence token id i
    for w in range(16):
        vocab.add_token(VocabWord(f"w{w}", 1000 - w))
    vocab.update_indices()
    return vocab, seqs


def topic_separation(syn0: np.ndarray) -> float:
    """mean(in-topic cosine) - mean(cross-topic cosine); strongly positive
    for any successful run."""
    m = syn0 / np.maximum(np.linalg.norm(syn0, axis=1, keepdims=True), 1e-9)
    sim = m @ m.T
    a, b = np.array(TOPIC_A), np.array(TOPIC_B)
    in_a = sim[np.ix_(a, a)][np.triu_indices(len(a), 1)]
    in_b = sim[np.ix_(b, b)][np.triu_indices(len(b), 1)]
    cross = sim[np.ix_(a, b)].ravel()
    return float(np.concatenate([in_a, in_b]).mean() - cross.mean())
