"""VAE, Yolo2OutputLayer, CnnLossLayer tests.

Models the reference's ``TestVAE``/``CNNGradientCheckTest``/YOLO suites
(SURVEY.md §4.1-4.2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BernoulliReconstructionDistribution,
    CnnLossLayer,
    CompositeReconstructionDistribution,
    ConvolutionLayer,
    DenseLayer,
    GaussianReconstructionDistribution,
    OutputLayer,
    VariationalAutoencoder,
    Yolo2OutputLayer,
    non_max_suppression,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.updaters import Adam


class TestVAE:
    def _vae_net(self, n_in=6, latent=3, dist=None):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(0.01))
            .list()
            .layer(VariationalAutoencoder(
                n_out=latent,
                encoder_layer_sizes=[12],
                decoder_layer_sizes=[12],
                activation="tanh",
                reconstruction_distribution=dist or GaussianReconstructionDistribution(),
            ))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    def test_param_shapes(self):
        net = self._vae_net()
        p = net.params_[0]
        assert p["eW0"].shape == (6, 12)
        assert p["pZXMeanW"].shape == (12, 3)
        assert p["pZXLogStd2W"].shape == (12, 3)
        assert p["dW0"].shape == (3, 12)
        assert p["pXZW"].shape == (12, 12)  # gaussian: 2 params/feature

    def test_supervised_forward_is_latent_mean(self):
        net = self._vae_net()
        x = np.random.default_rng(0).standard_normal((4, 6)).astype(np.float32)
        layer = net.layers[0]
        mean, _ = layer.encode_mean_logvar(net.params_[0], jnp.asarray(x))
        y, _ = layer.apply(net.params_[0], jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(y), np.asarray(mean))

    def test_pretrain_reduces_elbo_loss(self):
        rng = np.random.default_rng(0)
        # structured data: 2 clusters
        x = np.concatenate([
            rng.standard_normal((64, 6)).astype(np.float32) * 0.3 + 1.0,
            rng.standard_normal((64, 6)).astype(np.float32) * 0.3 - 1.0,
        ])
        net = self._vae_net()
        it = ListDataSetIterator(DataSet(x, None), 32)
        layer = net.layers[0]
        loss0 = float(layer.pretrain_loss(net.params_[0], jnp.asarray(x),
                                          jax.random.PRNGKey(0)))
        net.pretrain_layer(0, it, epochs=30)
        loss1 = float(layer.pretrain_loss(net.params_[0], jnp.asarray(x),
                                          jax.random.PRNGKey(0)))
        assert loss1 < loss0, f"-ELBO should fall: {loss0} -> {loss1}"

    def test_reconstruct_and_generate(self):
        net = self._vae_net()
        layer = net.layers[0]
        x = np.random.default_rng(1).standard_normal((4, 6)).astype(np.float32)
        recon = np.asarray(layer.reconstruct(net.params_[0], x))
        assert recon.shape == (4, 6)
        z = np.zeros((2, 3), np.float32)
        gen = np.asarray(layer.generate_at_mean_given_z(net.params_[0], z))
        assert gen.shape == (2, 6)
        lp = np.asarray(layer.reconstruction_log_probability(net.params_[0], x, 5))
        assert lp.shape == (4,)
        assert np.all(np.isfinite(lp))

    def test_bernoulli_distribution(self):
        dist = BernoulliReconstructionDistribution()
        x = jnp.asarray([[1.0, 0.0, 1.0]])
        logits = jnp.asarray([[2.0, -2.0, 0.0]])
        lp = dist.log_probability(x, logits)
        # manual: log σ(2) + log(1-σ(-2)) + log σ(0)
        import math

        sig = lambda v: 1 / (1 + math.exp(-v))
        expect = math.log(sig(2)) + math.log(1 - sig(-2)) + math.log(sig(0))
        assert float(lp[0]) == pytest.approx(expect, rel=1e-5)

    def test_composite_distribution(self):
        comp = (CompositeReconstructionDistribution()
                .add(2, GaussianReconstructionDistribution())
                .add(3, BernoulliReconstructionDistribution()))
        assert comp.total_params() == 2 * 2 + 3
        net = self._vae_net(n_in=5, dist=comp)
        p = net.params_[0]
        assert p["pXZW"].shape == (12, 7)
        x = np.random.default_rng(0).random((4, 5)).astype(np.float32)
        loss = float(net.layers[0].pretrain_loss(p, jnp.asarray(x), jax.random.PRNGKey(0)))
        assert np.isfinite(loss)

    def test_serde_roundtrip(self):
        net = self._vae_net()
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        l2 = conf2.layers[0]
        assert isinstance(l2, VariationalAutoencoder)
        assert l2.encoder_layer_sizes == [12]
        assert isinstance(l2.reconstruction_distribution, GaussianReconstructionDistribution)

    def test_vae_in_supervised_net_trains(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        net = self._vae_net()
        net.fit(DataSet(x, y), epochs=20)
        acc = net.evaluate(DataSet(x, y)).accuracy()
        assert acc > 0.8


class TestAutoEncoderPretrain:
    def test_greedy_pretrain(self):
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder

        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        conf = (
            NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(AutoEncoder(n_out=4, activation="sigmoid", corruption_level=0.1))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        layer = net.layers[0]
        l0 = float(layer.pretrain_loss(net.params_[0], jnp.asarray(x), jax.random.PRNGKey(1)))
        net.pretrain(ListDataSetIterator(DataSet(x, None), 32), epochs=20)
        l1 = float(layer.pretrain_loss(net.params_[0], jnp.asarray(x), jax.random.PRNGKey(1)))
        assert l1 < l0


class TestCnnLossLayer:
    def test_per_position_loss_and_training(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 6, 6, 1)).astype(np.float32)
        # per-pixel binary task: positive where input > 0
        labels = np.concatenate([(x > 0).astype(np.float32),
                                 (x <= 0).astype(np.float32)], axis=-1)
        conf = (
            NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
            .list()
            .layer(ConvolutionLayer(kernel_size=(1, 1), n_out=2, activation="identity"))
            .layer(CnnLossLayer(loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional(6, 6, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        net.fit(DataSet(x, labels), epochs=30)
        out = net.output(x)
        assert out.shape == (8, 6, 6, 2)
        pred = out.argmax(-1)
        truth = labels.argmax(-1)
        assert (pred == truth).mean() > 0.95


class TestYolo2:
    def _make_label(self, H=4, W=4, C=3):
        """One object: class 1, box centered in cell (1,2)."""
        lab = np.zeros((1, H, W, 4 + C), np.float32)
        # box x1,y1,x2,y2 in grid units; center (2.5, 1.5) → cell row1,col2
        lab[0, 1, 2, :4] = [2.1, 1.2, 2.9, 1.8]
        lab[0, 1, 2, 4 + 1] = 1.0
        return lab

    def _net(self, H=4, W=4, B=2, C=3, channels=16):
        # 16 input channels: the 1x1 head needs >= H*W*B*(5+C) effective
        # params to fit per-cell targets, else the no-object penalty pins
        # confidence down (overdetermined least-squares compromise)
        priors = [[1.0, 1.0], [2.5, 2.5]]
        conf = (
            NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list()
            .layer(ConvolutionLayer(kernel_size=(1, 1), n_out=B * (5 + C), activation="identity"))
            .layer(Yolo2OutputLayer(bounding_box_priors=priors))
            .set_input_type(InputType.convolutional(H, W, channels))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    def test_loss_finite_and_trains(self):
        H = W = 4
        net = self._net()
        x = np.random.default_rng(0).standard_normal((1, H, W, 16)).astype(np.float32)
        lab = self._make_label()
        ds = DataSet(x, lab)
        s0 = net.score(ds)
        assert np.isfinite(s0)
        net.fit(ds, epochs=60)
        s1 = net.score(ds)
        assert s1 < s0, f"YOLO loss should fall: {s0} -> {s1}"

    def test_detection_decoding(self):
        net = self._net()
        x = np.random.default_rng(0).standard_normal((1, 4, 4, 16)).astype(np.float32)
        lab = self._make_label()
        net.fit(DataSet(x, lab), epochs=200)
        activated = net.output(x)
        yolo = net.layers[-1]
        objs = yolo.get_predicted_objects(activated, threshold=0.5)
        objs = non_max_suppression(objs, 0.45)
        assert len(objs) >= 1
        best = max(objs, key=lambda o: o.confidence)
        assert best.predicted_class == 1
        # center near (2.5, 1.5) grid units
        assert abs(best.center_x - 2.5) < 0.6
        assert abs(best.center_y - 1.5) < 0.6

    def test_nms_suppresses_overlaps(self):
        from deeplearning4j_tpu.nn.conf.layers import DetectedObject

        a = DetectedObject(0, 2.0, 2.0, 1.0, 1.0, 0, 0.9)
        b = DetectedObject(0, 2.05, 2.0, 1.0, 1.0, 0, 0.8)  # big overlap
        c = DetectedObject(0, 5.0, 5.0, 1.0, 1.0, 0, 0.7)   # far away
        kept = non_max_suppression([a, b, c], 0.45)
        assert len(kept) == 2
        assert a in kept and c in kept


class TestGraphPretrain:
    def test_vae_pretrain_in_computation_graph(self):
        """ComputationGraph pretrain (reference ComputationGraph.pretrain):
        greedy unsupervised VAE pretraining reduces -ELBO, leaving other
        vertices untouched."""
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (
            NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
            .weight_init("xavier").graph_builder()
            .add_inputs("in")
            .add_layer("vae", VariationalAutoencoder(
                n_out=4, encoder_layer_sizes=[16], decoder_layer_sizes=[16],
            ), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "vae")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(8))
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        ds = DataSet(x, np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)])
        it = ListDataSetIterator(ds, 32)
        out_before = {k: np.asarray(v) for k, v in net.params_["out"].items()}
        losses = []
        for _ in range(15):
            net.pretrain(it, epochs=1)
            losses.append(float(net.score_))
        assert losses[-1] < losses[0], losses
        # only the VAE vertex trained
        for k, v in net.params_["out"].items():
            np.testing.assert_array_equal(np.asarray(v), out_before[k])
        # supervised fit still works afterwards
        net.fit(ds, batch_size=32)
        assert np.isfinite(float(net.score_))
