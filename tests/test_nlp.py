"""NLP suite tests, mirroring the reference's word2vec sanity/similarity
tests (``deeplearning4j-nlp/src/test`` — loss decreases on a real small
corpus; words that share contexts end up similar; serialization
round-trips; SURVEY.md §4.9).
"""

import os
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    AbstractCache,
    BagOfWordsVectorizer,
    BasicLineIterator,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    Huffman,
    NGramTokenizerFactory,
    ParagraphVectors,
    StopWords,
    TfidfVectorizer,
    VocabConstructor,
    Word2Vec,
    WordVectorSerializer,
)


# --------------------------------------------------------------------------
# synthetic two-topic corpus: animal words co-occur, tool words co-occur
# --------------------------------------------------------------------------
ANIMALS = ["cat", "dog", "horse", "cow", "sheep"]
TOOLS = ["hammer", "wrench", "drill", "saw", "pliers"]


def topic_corpus(n_sentences=400, seed=3):
    rng = np.random.default_rng(seed)
    sents = []
    for _ in range(n_sentences):
        group = ANIMALS if rng.random() < 0.5 else TOOLS
        words = rng.choice(group, size=6, replace=True)
        sents.append(" ".join(words))
    return sents


# --------------------------------------------------------------------------
# pipeline pieces
# --------------------------------------------------------------------------
class TestTokenization:
    def test_default_tokenizer(self):
        tf = DefaultTokenizerFactory()
        toks = tf.create("The quick brown fox").get_tokens()
        assert toks == ["The", "quick", "brown", "fox"]

    def test_common_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        toks = tf.create("Hello, World! 123 (test)").get_tokens()
        assert toks == ["hello", "world", "test"]

    def test_streaming_matches_batch(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        t = tf.create("Ab, 12 cd!")
        streamed = []
        while t.has_more_tokens():
            streamed.append(t.next_token())
        assert streamed == tf.create("Ab, 12 cd!").get_tokens()

    def test_ngrams(self):
        tf = NGramTokenizerFactory(DefaultTokenizerFactory(), 1, 2)
        toks = tf.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestIterators:
    def test_collection_iterator_reset(self):
        it = CollectionSentenceIterator(["one", "two"])
        assert list(it) == ["one", "two"]
        assert list(it) == ["one", "two"]  # reset via __iter__

    def test_line_iterator(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("first line\nsecond line\n")
        with BasicLineIterator(str(p)) as it:
            assert list(it) == ["first line", "second line"]


class TestVocab:
    def test_counts_indices_pruning(self):
        streams = [["a", "b", "a"], ["a", "c"]]
        cache = VocabConstructor(min_word_frequency=2).build_joint_vocabulary(
            streams
        )
        assert cache.contains_word("a")
        assert not cache.contains_word("b")
        assert cache.index_of("a") == 0  # most frequent first
        assert cache.word_frequency("a") == 3

    def test_stop_words_excluded(self):
        streams = [["the", "cat", "the", "dog"]]
        cache = VocabConstructor(
            min_word_frequency=1, stop_words=StopWords.get_stop_words()
        ).build_joint_vocabulary(streams)
        assert not cache.contains_word("the")
        assert cache.contains_word("cat")

    def test_huffman_codes(self):
        streams = [["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]]
        cache = VocabConstructor(min_word_frequency=1).build_joint_vocabulary(
            streams
        )
        h = Huffman(cache).build()
        words = {w.word: w for w in cache.vocab_words()}
        # most frequent word gets the shortest code
        assert len(words["a"].codes) <= len(words["d"].codes)
        # prefix-free: no code is a prefix of another
        codes = ["".join(map(str, w.codes)) for w in cache.vocab_words()]
        for i, c1 in enumerate(codes):
            for j, c2 in enumerate(codes):
                if i != j:
                    assert not c2.startswith(c1)
        codes_arr, points_arr, lengths = h.padded_arrays()
        assert codes_arr.shape == points_arr.shape
        assert int(lengths.max()) == h.max_code_length
        # inner-node ids are valid syn1 rows
        assert points_arr.max() < cache.num_words() - 1


# --------------------------------------------------------------------------
# Word2Vec end-to-end
# --------------------------------------------------------------------------
class TestWord2Vec:
    def _fit(self, **kw):
        defaults = dict(
            negative=5, hs=False, algorithm="skipgram", epochs=3, lr=0.05,
        )
        defaults.update(kw)
        b = (
            Word2Vec.builder()
            .iterate(topic_corpus())
            .layer_size(24)
            .window_size(3)
            .min_word_frequency(2)
            .seed(11)
            .learning_rate(defaults["lr"])
            .epochs(defaults["epochs"])
            .batch_size(256)
            .negative_sample(defaults["negative"])
            .use_hierarchic_softmax(defaults["hs"])
            .elements_learning_algorithm(defaults["algorithm"])
        )
        return b.build().fit()

    def _assert_topic_structure(self, w2v, margin=0.2):
        within = np.mean([
            w2v.similarity(a, b)
            for a in ANIMALS for b in ANIMALS if a != b
        ])
        across = np.mean([
            w2v.similarity(a, t) for a in ANIMALS for t in TOOLS
        ])
        assert within > across + margin, (
            f"within-topic {within:.3f} not above cross-topic {across:.3f}"
        )

    def test_skipgram_negative_sampling_learns_topics(self):
        w2v = self._fit()
        assert np.isfinite(w2v.last_loss)
        self._assert_topic_structure(w2v)
        # nearest neighbours of an animal are mostly animals
        near = w2v.words_nearest("cat", 3)
        assert sum(w in ANIMALS for w in near) >= 2

    def test_skipgram_hierarchical_softmax(self):
        # HS on a 10-word vocab shares most of the Huffman path between
        # words → separation is slower; more epochs, smaller margin
        w2v = self._fit(negative=0, hs=True, epochs=10)
        self._assert_topic_structure(w2v, margin=0.05)

    def test_cbow(self):
        # CBOW's per-row mean updates need more passes on a tiny vocab
        w2v = self._fit(algorithm="CBOW", epochs=20, lr=0.1)
        self._assert_topic_structure(w2v)

    def test_loss_decreases(self):
        w2v = (
            Word2Vec.builder().iterate(topic_corpus()).layer_size(16)
            .window_size(3).min_word_frequency(2).seed(5).learning_rate(0.05)
            .epochs(5).batch_size(256).negative_sample(5).build().fit()
        )
        losses = w2v.sv.epoch_losses
        assert len(losses) == 5
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    def test_unknown_word_handling(self):
        w2v = self._fit()
        assert w2v.get_word_vector("zebra") is None
        assert np.isnan(w2v.similarity("zebra", "cat"))
        assert w2v.words_nearest("zebra") == []


class TestSerialization:
    def _small_model(self):
        return (
            Word2Vec.builder().iterate(topic_corpus(100)).layer_size(8)
            .window_size(2).min_word_frequency(2).seed(1).epochs(1)
            .batch_size(128).negative_sample(3).build().fit()
        )

    def test_text_roundtrip(self, tmp_path):
        w2v = self._small_model()
        p = str(tmp_path / "vecs.txt")
        WordVectorSerializer.write_word_vectors(w2v, p)
        loaded = WordVectorSerializer.read_word_vectors(p)
        for w in w2v.vocab.words():
            np.testing.assert_allclose(
                loaded.get_word_vector(w), w2v.get_word_vector(w), atol=1e-5
            )
        # similarity structure preserved
        assert loaded.similarity("cat", "dog") == pytest.approx(
            w2v.similarity("cat", "dog"), abs=1e-4
        )

    def test_binary_roundtrip(self, tmp_path):
        w2v = self._small_model()
        p = str(tmp_path / "vecs.bin")
        WordVectorSerializer.write_word_vectors_binary(w2v, p)
        loaded = WordVectorSerializer.read_word_vectors_binary(p)
        for w in w2v.vocab.words():
            np.testing.assert_allclose(
                loaded.get_word_vector(w), w2v.get_word_vector(w), atol=1e-6
            )


# --------------------------------------------------------------------------
# ParagraphVectors
# --------------------------------------------------------------------------
class TestParagraphVectors:
    def _docs(self, n=60, seed=9):
        rng = np.random.default_rng(seed)
        docs = []
        for k in range(n):
            topic = "animals" if k % 2 == 0 else "tools"
            group = ANIMALS if topic == "animals" else TOOLS
            words = rng.choice(group, size=8, replace=True)
            docs.append((" ".join(words), [f"doc_{k}", topic]))
        return docs

    def test_dbow_label_vectors_cluster_by_topic(self):
        pv = (
            ParagraphVectors.builder().iterate(self._docs())
            .layer_size(16).min_word_frequency(1).epochs(3)
            .negative_sample(5).seed(4).learning_rate(0.05)
            .batch_size(128).build().fit()
        )
        sim_same = pv.similarity("animals", "tools")
        v_animals = pv.get_paragraph_vector("animals")
        v_tools = pv.get_paragraph_vector("tools")
        assert v_animals is not None and v_tools is not None
        # an animal doc label should be closer to "animals" than "tools"
        same = np.mean([pv.similarity("doc_0", "animals"),
                        pv.similarity("doc_2", "animals")])
        cross = np.mean([pv.similarity("doc_0", "tools"),
                         pv.similarity("doc_2", "tools")])
        assert same > cross

    def test_dm_trains(self):
        pv = (
            ParagraphVectors.builder().iterate(self._docs(30))
            .layer_size(12).epochs(2).negative_sample(3).seed(4)
            .sequence_learning_algorithm("DM").batch_size(64).build().fit()
        )
        assert pv.get_paragraph_vector("animals") is not None

    def test_dm_infer_vector_uses_dm_objective(self):
        """reference inferVector runs the CONFIGURED algorithm: a
        DM-trained model infers through the context-mean objective
        (kernels.dm_infer_step), and the result lands on the right
        topic side."""
        pv = (
            ParagraphVectors.builder().iterate(self._docs())
            .layer_size(16).epochs(3).negative_sample(5).seed(4)
            .learning_rate(0.05).sequence_learning_algorithm("DM")
            .batch_size(128).build().fit()
        )
        v = pv.infer_vector("cat dog horse cow sheep cat dog")
        assert v.shape == (16,)
        assert np.all(np.isfinite(v))
        assert np.abs(v).max() > 0  # moved off zero

        def sim(v, label):
            u = pv.get_paragraph_vector(label)
            return float(v @ u / (np.linalg.norm(v) * np.linalg.norm(u)
                                  + 1e-9))

        assert sim(v, "animals") > sim(v, "tools"), (
            sim(v, "animals"), sim(v, "tools"))
        # single-token text (no full window) falls back to DBOW inference
        v1 = pv.infer_vector("cat")
        assert np.all(np.isfinite(v1))

    def test_infer_vector_nearest_label(self):
        pv = (
            ParagraphVectors.builder().iterate(self._docs())
            .layer_size(16).epochs(3).negative_sample(5).seed(4)
            .learning_rate(0.05).batch_size(128).build().fit()
        )
        v = pv.infer_vector("cat dog horse cow")
        assert v.shape == (16,)
        assert np.all(np.isfinite(v))
        labels = pv.nearest_labels("cat dog horse cow sheep cat", n=4)
        assert len(labels) == 4

    def test_paragraph_vectors_zip_round_trip(self, tmp_path):
        """reference WordVectorSerializer.writeParagraphVectors /
        readParagraphVectors: the restored model reproduces doc-vector
        queries exactly and infer_vector works (syn1neg restored)."""
        from deeplearning4j_tpu.nlp.serializer import (
            read_paragraph_vectors,
            write_paragraph_vectors,
        )

        pv = (
            ParagraphVectors.builder().iterate(self._docs())
            .layer_size(16).min_word_frequency(1).epochs(3)
            .negative_sample(5).seed(4).learning_rate(0.05)
            .batch_size(128).build().fit()
        )
        p = str(tmp_path / "pv.zip")
        write_paragraph_vectors(pv, p)
        back = read_paragraph_vectors(p)

        assert back.label_index == pv.label_index
        for label in ("animals", "tools", "doc_0"):
            np.testing.assert_array_equal(
                back.get_paragraph_vector(label),
                pv.get_paragraph_vector(label))
        assert back.similarity("doc_0", "animals") == pytest.approx(
            pv.similarity("doc_0", "animals"))
        # infer_vector exercises the restored syn1neg + vocab
        np.testing.assert_allclose(
            back.infer_vector("cat dog horse"),
            pv.infer_vector("cat dog horse"), atol=1e-6)


# --------------------------------------------------------------------------
# GloVe
# --------------------------------------------------------------------------
class TestGlove:
    def test_glove_learns_topics(self):
        g = (
            Glove.builder().iterate(topic_corpus(300)).layer_size(16)
            .window_size(3).min_word_frequency(2).epochs(8)
            .learning_rate(0.1).seed(2).batch_size(512).build().fit()
        )
        assert np.isfinite(g.last_loss)
        within = np.mean([
            g.similarity(a, b) for a in ANIMALS for b in ANIMALS if a != b
        ])
        across = np.mean([g.similarity(a, t) for a in ANIMALS for t in TOOLS])
        assert within > across, f"within {within:.3f} <= across {across:.3f}"


# --------------------------------------------------------------------------
# Bag of words / TF-IDF
# --------------------------------------------------------------------------
class TestVectorizers:
    def test_bow_counts(self):
        v = (
            BagOfWordsVectorizer.builder()
            .iterate(["cat dog cat", "dog hammer"])
            .min_word_frequency(1).build().fit()
        )
        x = v.transform("cat cat dog")
        assert x[v.vocab.index_of("cat")] == 2.0
        assert x[v.vocab.index_of("dog")] == 1.0

    def test_tfidf_downweights_common_terms(self):
        v = (
            TfidfVectorizer.builder()
            .iterate(["cat dog", "cat hammer", "cat wrench"])
            .min_word_frequency(1).build().fit()
        )
        x = v.transform("cat hammer")
        # "cat" appears in every doc → lower idf than "hammer"
        assert x[v.vocab.index_of("hammer")] > x[v.vocab.index_of("cat")]

    def test_transform_all_shape(self):
        v = (
            BagOfWordsVectorizer.builder().iterate(["a b", "b c"])
            .min_word_frequency(1).build().fit()
        )
        m = v.transform_all(["a", "b c"])
        assert m.shape == (2, v.vocab.num_words())

    def test_text_roundtrip_with_spaced_ngram_tokens(self):
        """Tokens containing spaces (n-grams) must survive the text
        format (reader splits from the right)."""
        from deeplearning4j_tpu.nlp.serializer import _StaticWordVectors
        import tempfile, os
        words = ["new york", "cat", "san francisco bay"]
        m = np.arange(9, dtype=np.float32).reshape(3, 3)
        sw = _StaticWordVectors(words, m)
        p = os.path.join(tempfile.mkdtemp(), "ng.txt")
        WordVectorSerializer.write_word_vectors(sw, p)
        loaded = WordVectorSerializer.read_word_vectors(p)
        for w in words:
            np.testing.assert_allclose(loaded.get_word_vector(w),
                                       sw.get_word_vector(w), atol=1e-5)


class TestTokenizerPlugins:
    def test_chinese_per_char_and_lexicon(self):
        from deeplearning4j_tpu.nlp.tokenization_plugins import (
            ChineseTokenizerFactory,
        )

        tf = ChineseTokenizerFactory()
        assert tf.create("我爱北京").get_tokens() == ["我", "爱", "北", "京"]
        tf2 = ChineseTokenizerFactory(lexicon={"北京"})
        assert tf2.create("我爱北京").get_tokens() == ["我", "爱", "北京"]

    def test_chinese_mixed_latin(self):
        from deeplearning4j_tpu.nlp.tokenization_plugins import (
            ChineseTokenizerFactory,
        )

        toks = ChineseTokenizerFactory().create("我用 jax 框架").get_tokens()
        assert "jax" in toks and "我" in toks

    def test_japanese_kana_runs_kept(self):
        from deeplearning4j_tpu.nlp.tokenization_plugins import (
            JapaneseTokenizerFactory,
        )

        toks = JapaneseTokenizerFactory().create("これは漢字です").get_tokens()
        assert "これは" in toks  # kana run whole
        assert "漢" in toks and "字" in toks  # kanji per char

    def test_korean_particle_split(self):
        from deeplearning4j_tpu.nlp.tokenization_plugins import (
            KoreanTokenizerFactory,
        )

        toks = KoreanTokenizerFactory().create("고양이는 귀엽다").get_tokens()
        assert toks[0] == "고양이" and toks[1] == "는"

class TestFullModelZip:
    def test_full_model_zip_roundtrip_and_resume(self, tmp_path):
        """Full-model zip (reference writeWord2VecModel): queries match
        after load AND training resumes on the restored tables."""
        w2v = TestSerialization()._small_model()
        p = str(tmp_path / "w2v_full.zip")
        WordVectorSerializer.write_word2vec_model(w2v, p)
        loaded = WordVectorSerializer.read_word2vec_model(p)
        for w in w2v.vocab.words():
            np.testing.assert_allclose(
                loaded.get_word_vector(w), w2v.get_word_vector(w), atol=1e-6
            )
        assert loaded.similarity("cat", "dog") == pytest.approx(
            w2v.similarity("cat", "dog"), abs=1e-5
        )
        # resume: further fitting moves the vectors (tables are live)
        before = loaded.get_word_vector("cat").copy()
        ids = np.asarray([loaded.vocab.index_of(w)
                          for w in ("cat", "dog", "cat", "dog")], np.int32)
        loaded.sv.fit_sequences([ids])
        moved = np.abs(loaded.get_word_vector("cat") - before).max()
        assert moved > 0, "restored tables did not train"


class TestInvertedIndex:
    """reference text/invertedindex/InvertedIndex.java."""

    def _index(self):
        from deeplearning4j_tpu.nlp import InMemoryInvertedIndex

        idx = InMemoryInvertedIndex()
        idx.add_document("the quick brown fox".split(), label="a")
        idx.add_document("the lazy dog".split(), label="b")
        idx.add_document("quick quick dog".split(), label="a")
        return idx

    def test_postings_and_documents(self):
        idx = self._index()
        assert idx.num_documents() == 3
        assert idx.documents("quick") == [0, 2]
        assert idx.documents("dog") == [1, 2]
        assert idx.documents("missing") == []
        assert idx.document(1) == ["the", "lazy", "dog"]
        doc, label = idx.document_with_label(2)
        assert label == "a" and doc[0] == "quick"

    def test_frequencies(self):
        idx = self._index()
        assert idx.doc_frequency("quick") == 2
        assert idx.term_frequency("quick") == 3
        assert idx.doc_frequency("the") == 2

    def test_conjunctive_query(self):
        idx = self._index()
        assert idx.documents_containing_all(["quick", "dog"]) == [2]
        assert idx.documents_containing_all([]) == []

    def test_batch_iteration(self):
        idx = self._index()
        batches = list(idx.batch_iter(2))
        assert [len(b) for b in batches] == [2, 1]
        labels = [l for _, l in idx.each_doc_with_label()]
        assert labels == ["a", "b", "a"]


# --------------------------------------------------------------------------
# CnnSentenceDataSetIterator
# --------------------------------------------------------------------------
class TestCnnSentenceIterator:
    def _wv(self):
        from deeplearning4j_tpu.nlp.serializer import _StaticWordVectors

        words = ["cat", "dog", "fish", "rock", "iron", "zinc"]
        rng = np.random.default_rng(0)
        return _StaticWordVectors(words,
                                  rng.random((6, 8)).astype(np.float32))

    def test_shapes_masks_and_formats(self, tmp_path):
        """reference CnnSentenceDataSetIterator: labelled sentences ->
        padded word-vector stacks (NHWC here), mask, one-hot labels."""
        from deeplearning4j_tpu.nlp import (
            CnnSentenceDataSetIterator,
            CollectionLabeledSentenceProvider,
            FileLabeledSentenceProvider,
        )

        sents = ["cat dog fish", "rock iron", "dog dog cat fish",
                 "zinc rock iron iron"]
        labels = ["animal", "mineral", "animal", "mineral"]
        it = (CnnSentenceDataSetIterator.builder()
              .sentence_provider(
                  CollectionLabeledSentenceProvider(sents, labels))
              .word_vectors(self._wv())
              .minibatch_size(4).build())
        assert it.get_labels() == ["animal", "mineral"]
        ds = it.next()
        assert ds.features.shape == (4, 4, 8, 1)  # (b, maxlen, wv, 1)
        assert ds.labels.shape == (4, 2)
        np.testing.assert_array_equal(
            ds.features_mask,
            [[1, 1, 1, 0], [1, 1, 0, 0], [1, 1, 1, 1], [1, 1, 1, 1]])
        # padded positions are zero vectors
        assert np.all(ds.features[0, 3] == 0)
        it.reset()
        assert it.has_next()

        # cnn1d format + unknown-word removal
        it1 = (CnnSentenceDataSetIterator.builder()
               .sentence_provider(CollectionLabeledSentenceProvider(
                   ["cat UNKNOWNWORD dog"], ["animal"]))
               .word_vectors(self._wv())
               .data_format("cnn1d").build())
        d1 = it1.next()
        assert d1.features.shape == (1, 2, 8)  # unknown removed

        # file provider: label = parent dir
        for label, texts in [("pos", ["cat dog", "fish cat"]),
                             ("neg", ["rock iron"])]:
            d = tmp_path / label
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        fp = FileLabeledSentenceProvider(str(tmp_path))
        assert fp.total_num_sentences() == 3
        assert fp.all_labels() == ["neg", "pos"]

    def test_trains_text_cnn(self):
        """Kim-CNN smoke: a small Conv2D net learns to classify the
        two-topic sentences from the iterator's output format."""
        from deeplearning4j_tpu.nlp import (
            CnnSentenceDataSetIterator,
            CollectionLabeledSentenceProvider,
        )
        from deeplearning4j_tpu.nn.conf.builders import (
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer,
            GlobalPoolingLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import Adam

        rng = np.random.default_rng(1)
        animals, minerals = ["cat", "dog", "fish"], ["rock", "iron", "zinc"]
        sents, labels = [], []
        for _ in range(60):
            if rng.random() < 0.5:
                sents.append(" ".join(rng.choice(animals, 4)))
                labels.append("animal")
            else:
                sents.append(" ".join(rng.choice(minerals, 4)))
                labels.append("mineral")
        it = (CnnSentenceDataSetIterator.builder()
              .sentence_provider(
                  CollectionLabeledSentenceProvider(sents, labels))
              .word_vectors(self._wv())
              .max_sentence_length(4).minibatch_size(60).build())
        ds = it.next()
        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.02))
            .weight_init("xavier").list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(2, 8),
                                    stride=(1, 1), activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="max"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(4, 8, 1)).build()
        )
        net = MultiLayerNetwork(conf).init()
        for _ in range(30):
            net.fit(ds, batch_size=60)
        preds = net.output(ds.features).argmax(1)
        acc = float((preds == ds.labels.argmax(1)).mean())
        assert acc > 0.9, acc


class TestEndingPreProcessor:
    def test_reference_order(self):
        from deeplearning4j_tpu.nlp import EndingPreProcessor

        e = EndingPreProcessor()
        assert e.pre_process("dogs") == "dog"
        assert e.pre_process("glass") == "glass"   # ss kept
        assert e.pre_process("walked") == "walk"
        assert e.pre_process("quickly") == "quick"
        # reference applies the rules in sequence, so "things" loses the
        # "s" AND then the "ing": -> "th" (faithfully quirky)
        assert e.pre_process("things") == "th"


class TestCnnSentenceReviewRegressions:
    def test_has_next_contract_with_oov_tail(self):
        """has_next() must stay truthful when the stream tail is all-OOV
        (default remove mode): the epoch ends instead of crashing."""
        from deeplearning4j_tpu.nlp import (
            CnnSentenceDataSetIterator,
            CollectionLabeledSentenceProvider,
        )
        from deeplearning4j_tpu.nlp.serializer import _StaticWordVectors

        wv = _StaticWordVectors(["cat", "dog"],
                                np.ones((2, 4), np.float32))
        it = (CnnSentenceDataSetIterator.builder()
              .sentence_provider(CollectionLabeledSentenceProvider(
                  ["cat dog", "zzz qqq", "xxx yyy"],
                  ["a", "b", "b"]))
              .word_vectors(wv).minibatch_size(1).build())
        batches = list(it)  # must terminate cleanly
        assert len(batches) == 1
        assert batches[0].features.shape[0] == 1

    def test_use_unknown_is_order_independent(self):
        """OOV tokens become zero vectors even in the FIRST sentence —
        the vector size is probed eagerly from the table."""
        from deeplearning4j_tpu.nlp import (
            CnnSentenceDataSetIterator,
            CollectionLabeledSentenceProvider,
        )
        from deeplearning4j_tpu.nlp.serializer import _StaticWordVectors

        wv = _StaticWordVectors(["cat"], np.ones((1, 4), np.float32))
        it = (CnnSentenceDataSetIterator.builder()
              .sentence_provider(CollectionLabeledSentenceProvider(
                  ["zzz cat"], ["a"]))
              .word_vectors(wv)
              .unknown_word_handling("use_unknown")
              .data_format("cnn1d").build())
        ds = it.next()
        assert ds.features.shape == (1, 2, 4)  # OOV kept as zero vector
        assert np.all(ds.features[0, 0] == 0)
        assert np.all(ds.features[0, 1] == 1)
