"""Step-level fault tolerance (train/faults.py + threading through every
fit path).

Core contract (ISSUE 2 acceptance): with ``skip_nonfinite`` on, a fit
whose batch k produces NaN gradients finishes with params equal to the
same fit with batch k removed — EXACT for the replicated paths (the skip
is a jnp.where on the old buffers and the updater clock runs on the
in-graph good-step count, so trajectories coincide bit for bit), and
parity holds under the ZeRO-1 sharded update. Crash-safety: an
interrupted ``write_model`` never corrupts the previously visible
checkpoint, and ``load_latest_valid`` skips truncated/corrupt newest
checkpoints back to the last good one.

All tests here are single-process tier-1 speed; the multi-process
SIGKILL + truncation drill lives in test_multihost.py (slow tier).
"""

import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import faults
from deeplearning4j_tpu.train.faults import (
    FaultPolicy,
    TrainingDivergedError,
    fault_injection,
)
from deeplearning4j_tpu.updaters import Adam

N_IN, N_HID, N_OUT = 5, 7, 3


def _net(policy=None, mixed_precision=False, seed=3):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
    if mixed_precision:
        b = b.compute_dtype("bfloat16")
    if policy is not None:
        b = b.fault_policy(policy)
    conf = (
        b.list()
        .layer(DenseLayer(n_out=N_HID, activation="tanh"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _batches(n=4, per=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((per, N_IN)).astype(np.float32)
        y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, per)]
        out.append(DataSet(x, y))
    return out


class TestNonFiniteGuard:
    def test_nan_step_skipped_bit_identical(self):
        """Inject NaN grads at step 1 of 4: the run must equal the same
        fit with batch 1 removed — params AND updater state, exactly."""
        batches = _batches()
        with fault_injection(nan_grad_steps=[1]):
            a = _net(FaultPolicy())
            a.fit(ExistingDataSetIterator(batches))
        b = _net()
        b.fit(ExistingDataSetIterator(
            [batches[0], batches[2], batches[3]]))
        np.testing.assert_array_equal(a.params_flat(), b.params_flat())
        np.testing.assert_array_equal(a.opt_state_flat(), b.opt_state_flat())
        assert a.bad_step_count == 1
        assert int(a.fault_state_["good_count"]) == 3
        assert int(a.fault_state_["consec"]) == 0  # reset by good steps
        # the host iteration counter still counts every batch seen
        assert a.iteration == 4

    def test_guard_enabled_without_faults_is_a_noop(self):
        batches = _batches()
        a = _net(FaultPolicy())
        a.fit(ExistingDataSetIterator(batches), epochs=2)
        b = _net()
        b.fit(ExistingDataSetIterator(batches), epochs=2)
        np.testing.assert_array_equal(a.params_flat(), b.params_flat())
        assert a.bad_step_count == 0

    def test_max_consecutive_bad_steps_raises(self):
        batches = _batches()
        with fault_injection(nan_grad_steps=[0, 1, 2, 3]):
            a = _net(FaultPolicy(max_consecutive_bad_steps=2))
            with pytest.raises(TrainingDivergedError, match="consecutive"):
                a.fit(ExistingDataSetIterator(batches))
        assert a.bad_step_count == 2  # raised at the limit, not after

    def test_nonconsecutive_bad_steps_do_not_raise(self):
        batches = _batches()
        with fault_injection(nan_grad_steps=[0, 2]):
            a = _net(FaultPolicy(max_consecutive_bad_steps=2))
            a.fit(ExistingDataSetIterator(batches))
        assert a.bad_step_count == 2

    def test_computation_graph_guard(self):
        """The same skip-exactness through the ComputationGraph step."""
        batches = _batches()
        with fault_injection(nan_grad_steps=[1]):
            a = _net(FaultPolicy()).to_computation_graph()
            a.fit(ExistingDataSetIterator(batches))
        b = _net().to_computation_graph()
        b.fit(ExistingDataSetIterator([batches[0], batches[2], batches[3]]))
        for name in a.layer_names:
            for k in a.params_[name]:
                np.testing.assert_array_equal(
                    np.asarray(a.params_[name][k]),
                    np.asarray(b.params_[name][k]))
        assert a.bad_step_count == 1

    def test_tbptt_chunk_guard_skips_batch(self):
        """tBPTT path: a poisoned batch (all its chunks) leaves params,
        opt state and carries untouched; clean batches still train."""
        from deeplearning4j_tpu.nn.conf.layers import (
            RnnOutputLayer,
            SimpleRnn,
        )

        def rnn_net(policy=None):
            b = NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            if policy is not None:
                b = b.fault_policy(policy)
            conf = (
                b.list()
                .layer(SimpleRnn(n_out=6))
                .layer(RnnOutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(4, 8))
                .backprop_type("tbptt", fwd_length=4, back_length=4)
                .build()
            )
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(1)
        x = rng.standard_normal((6, 8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[
            rng.integers(0, 2, (6, 8))].astype(np.float32)
        ds = DataSet(x, y)
        with fault_injection(nan_grad_steps=[1]):
            n = rnn_net(FaultPolicy())
            n.fit(ds, epochs=1, batch_size=6)  # iteration 0: clean
            before = n.params_flat().copy()
            n.fit(ds, epochs=1, batch_size=6)  # iteration 1: poisoned
            np.testing.assert_array_equal(before, n.params_flat())
            n.fit(ds, epochs=1, batch_size=6)  # trains again
        assert n.bad_step_count == 2  # both chunks of the bad batch
        assert not np.array_equal(before, n.params_flat())
        assert np.isfinite(n.params_flat()).all()

    def test_policy_json_roundtrip(self):
        pol = FaultPolicy(max_consecutive_bad_steps=7, keep_last=2,
                          init_loss_scale=2.0 ** 10)
        net = _net(pol)
        clone = type(net.conf).from_json(net.conf.to_json())
        assert clone.global_conf.fault_policy == pol


class TestDynamicLossScaling:
    def test_backoff_and_regrow_trace(self):
        """bf16 compute: scale grows x2 after 2 good steps, halves on the
        injected overflow, then recovers — the canonical trace."""
        pol = FaultPolicy(init_loss_scale=2.0 ** 8, scale_growth_interval=2)
        ds = _batches(1)[0]
        with fault_injection(nan_grad_steps=[2]):
            n = _net(pol, mixed_precision=True)
            scales = []
            for _ in range(6):
                n.fit(ds, epochs=1, batch_size=8)
                scales.append(n.loss_scale)
        assert scales == [256.0, 512.0, 256.0, 256.0, 512.0, 512.0]
        assert n.bad_step_count == 1

    def test_scale_floor(self):
        pol = FaultPolicy(init_loss_scale=2.0, min_loss_scale=1.0,
                          scale_growth_interval=100)
        ds = _batches(1)[0]
        with fault_injection(nan_grad_steps=[0, 1, 2]):
            n = _net(pol, mixed_precision=True)
            for _ in range(3):
                n.fit(ds, epochs=1, batch_size=8)
        assert n.loss_scale == 1.0  # clamped, never 0

    def test_scaling_off_for_fp32(self):
        """Default loss_scaling=None only activates under compute_dtype."""
        n = _net(FaultPolicy())
        n.fit(_batches(1)[0], epochs=1, batch_size=8)
        assert n.loss_scale is None
        assert "loss_scale" not in n.fault_state_

    def test_skipped_step_params_unchanged_bf16(self):
        """Overflow-skipped step leaves bf16-compute params bit-identical."""
        pol = FaultPolicy(init_loss_scale=2.0 ** 8)
        ds = _batches(1)[0]
        with fault_injection(nan_grad_steps=[1]):
            n = _net(pol, mixed_precision=True)
            n.fit(ds, epochs=1, batch_size=8)
            before = n.params_flat().copy()
            n.fit(ds, epochs=1, batch_size=8)  # iteration 1 → injected
            after = n.params_flat().copy()
        np.testing.assert_array_equal(before, after)


class TestParallelPathsGuard:
    def test_wrapper_replicated_and_zero1_parity_with_guard(self):
        """ParallelWrapper with the guard: replicated run equals the
        batch-removed reference exactly; the ZeRO-1 sharded run (global
        pre-scatter verdict) matches the replicated one."""
        from deeplearning4j_tpu.parallel import ParallelWrapper

        ds = _batches(1, per=32)[0]
        with fault_injection(nan_grad_steps=[1]):
            repl = _net(FaultPolicy())
            ParallelWrapper.builder(repl).workers(4).build().fit(
                ExistingDataSetIterator([ds]), epochs=3)
            zero = _net(FaultPolicy())
            ParallelWrapper.builder(zero).workers(4).sharded_update(
                True).build().fit(ExistingDataSetIterator([ds]), epochs=3)
        removed = _net()
        ParallelWrapper.builder(removed).workers(4).build().fit(
            ExistingDataSetIterator([ds]), epochs=2)
        np.testing.assert_array_equal(repl.params_flat(),
                                      removed.params_flat())
        assert repl.bad_step_count == 1 and zero.bad_step_count == 1
        np.testing.assert_allclose(zero.params_flat(), repl.params_flat(),
                                   atol=1e-6)
        # gathered-back opt state stays canonical and matches
        np.testing.assert_allclose(zero.opt_state_flat(),
                                   repl.opt_state_flat(), atol=1e-6)

    def test_shared_master_skips_exactly(self):
        """SharedTrainingMaster guard: the poisoned step leaves params and
        the residual untouched; training continues finite."""
        from deeplearning4j_tpu.parallel import SharedTrainingMaster

        ds = _batches(1, per=32)[0]
        with fault_injection(nan_grad_steps=[1]):
            m = _net(FaultPolicy())
            master = SharedTrainingMaster.builder(1e-5).build()
            it = ExistingDataSetIterator([ds])
            master.fit(m, it, epochs=1)
            before = m.params_flat().copy()
            master.fit(m, it, epochs=1)  # iteration 1 → injected → skipped
            np.testing.assert_array_equal(before, m.params_flat())
            master.fit(m, it, epochs=1)
        assert m.bad_step_count == 1
        assert np.isfinite(m.params_flat()).all()
        assert np.isfinite(master.residual_magnitude())

    def test_transformer_trainer_guard_parity(self):
        """DistributedLMTrainer (fp32): guarded run with the poisoned
        batch equals the run without it; bf16 sharded_update variant
        stays finite with the scale backing off once."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import (
            DistributedLMTrainer,
        )

        V, T, B = 17, 8, 8
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (B, T)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tgt[:, -1] = -1

        def model(mp=False):
            kw = dict(vocab_size=V, d_model=16, n_heads=2, n_layers=1,
                      max_length=T)
            if mp:
                kw["compute_dtype"] = "bfloat16"
            return TransformerLM(**kw).init()

        with fault_injection(nan_grad_steps=[2]):
            tr = DistributedLMTrainer(model(), TrainingMesh(data=8),
                                      fault_policy=FaultPolicy()).place()
            for _ in range(4):
                tr.fit_batch(ids, tgt)
        ref = DistributedLMTrainer(model(), TrainingMesh(data=8)).place()
        for _ in range(3):
            ref.fit_batch(ids, tgt)
        for a, b in zip(jax.tree_util.tree_leaves(tr.model.params_),
                        jax.tree_util.tree_leaves(ref.model.params_)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert tr.bad_step_count == 1

        with fault_injection(nan_grad_steps=[2]):
            trz = DistributedLMTrainer(
                model(mp=True), TrainingMesh(data=8), sharded_update=True,
                fault_policy=FaultPolicy(init_loss_scale=2.0 ** 10,
                                         scale_growth_interval=100)).place()
            losses = [trz.fit_batch(ids, tgt) for _ in range(4)]
        assert np.isfinite(losses).all()
        assert trz.bad_step_count == 1
        assert trz.loss_scale == 2.0 ** 9  # one backoff
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(trz.model.params_))


class TestCrashSafeCheckpointing:
    def _ckpt(self, net, path):
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        ModelSerializer.write_model(net, path, save_updater=True)

    def test_failed_write_leaves_previous_checkpoint(self, tmp_path):
        """A write that dies mid-stream must neither corrupt the visible
        checkpoint nor leave staging debris behind."""
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = _net()
        net.fit(_batches(1)[0], epochs=1, batch_size=8)
        path = str(tmp_path / "model.zip")
        self._ckpt(net, path)
        good = net.params_flat().copy()

        broken = net.clone()
        broken.opt_state_flat = lambda: (_ for _ in ()).throw(
            RuntimeError("simulated crash mid-serialization"))
        with pytest.raises(RuntimeError, match="simulated crash"):
            self._ckpt(broken, path)
        assert faults.is_valid_checkpoint(path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        np.testing.assert_array_equal(restored.params_flat(), good)
        assert [f for f in os.listdir(tmp_path) if ".tmp-" in f] == []

    def test_truncated_newest_falls_back_to_previous(self, tmp_path):
        net = _net()
        ds = _batches(1)[0]
        net.fit(ds, epochs=1, batch_size=8)
        p1 = faults.save_checkpoint(net, str(tmp_path))
        net.fit(ds, epochs=1, batch_size=8)
        p2 = faults.save_checkpoint(net, str(tmp_path))
        assert p1 != p2
        faults.truncate_file(p2)  # SIGKILL-mid-write stand-in
        ok, reason = faults.validate_checkpoint(p2)
        assert not ok and reason

        with pytest.warns(UserWarning, match="corrupt checkpoint"):
            model, path = faults.load_latest_valid(str(tmp_path))
        assert path == p1
        assert model.iteration == 1  # the older (valid) state

    def test_all_corrupt_raises(self, tmp_path):
        net = _net()
        net.fit(_batches(1)[0], epochs=1, batch_size=8)
        p = faults.save_checkpoint(net, str(tmp_path))
        faults.truncate_file(p)
        with pytest.warns(UserWarning):
            with pytest.raises(FileNotFoundError, match="all corrupt"):
                faults.load_latest_valid(str(tmp_path))

    def test_keep_last_retention_and_tmp_sweep(self, tmp_path):
        net = _net()
        ds = _batches(1)[0]
        paths = []
        for _ in range(5):
            net.fit(ds, epochs=1, batch_size=8)
            paths.append(faults.save_checkpoint(net, str(tmp_path),
                                                keep_last=2))
        # stray staging file from a crashed writer is swept once it is
        # old enough to be debris; a FRESH one (a concurrent writer's
        # in-flight stage) is left alone
        stray = tmp_path / "model.zip.tmp-123-dead"
        stray.write_bytes(b"garbage")
        faults.prune_checkpoints(str(tmp_path), keep_last=2)
        assert stray.exists()  # too young to sweep
        old = __import__("time").time() - 2 * faults._TMP_SWEEP_AGE_S
        os.utime(stray, (old, old))
        faults.prune_checkpoints(str(tmp_path), keep_last=2)
        left = sorted(os.listdir(tmp_path))
        assert left == sorted(os.path.basename(p) for p in paths[-2:])
        # newest valid is the last one written
        assert faults.latest_valid_checkpoint(str(tmp_path)) == paths[-1]

    def test_load_model_guess_names_path_and_entries(self, tmp_path):
        from deeplearning4j_tpu.train.model_serializer import ModelGuesser

        path = str(tmp_path / "notamodel.zip")
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("readme.txt", "hello")
            z.writestr("data.bin", b"\x00\x01")
        with pytest.raises(ValueError) as ei:
            ModelGuesser.load_model_guess(path)
        msg = str(ei.value)
        assert "notamodel.zip" in msg
        assert "readme.txt" in msg and "data.bin" in msg
        assert "configuration.json" in msg  # what was expected

    def test_save_load_resume_through_guarded_fit(self, tmp_path):
        """Checkpoint-resume with the guard on: good_count re-seeds from
        the restored iteration so the Adam clock keeps running."""
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        ds = _batches(1)[0]
        a = _net(FaultPolicy())
        a.fit(ds, epochs=2, batch_size=8)
        path = str(tmp_path / "ck.zip")
        self._ckpt(a, path)
        resumed = ModelSerializer.restore_multi_layer_network(path)
        resumed.fit(ds, epochs=2, batch_size=8)

        b = _net(FaultPolicy())
        b.fit(ds, epochs=4, batch_size=8)
        np.testing.assert_allclose(resumed.params_flat(), b.params_flat(),
                                   atol=1e-6)


class TestEarlyStoppingSatellites:
    def _es_parts(self):
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator,
            EarlyStoppingConfiguration,
            EarlyStoppingTrainer,
            MaxEpochsTerminationCondition,
        )

        return (DataSetLossCalculator, EarlyStoppingConfiguration,
                EarlyStoppingTrainer, MaxEpochsTerminationCondition)

    def test_nan_epoch_score_terminates_with_error(self):
        """An empty evaluation iterator yields a NaN score; the trainer
        must stop with an Error termination instead of looping to
        MaxEpochs without ever saving a best model."""
        (DataSetLossCalculator, EarlyStoppingConfiguration,
         EarlyStoppingTrainer, MaxEpochsTerminationCondition) = \
            self._es_parts()

        net = _net()
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(
                ExistingDataSetIterator([])),  # empty → NaN
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(50)],
        )
        result = EarlyStoppingTrainer(
            cfg, net, ExistingDataSetIterator(_batches(1))).fit()
        assert result.termination_reason == "Error"
        assert "NaN" in result.termination_details
        assert result.total_epochs == 1  # stopped immediately, not at 50

    def test_max_time_clock_starts_at_fit_entry(self, monkeypatch):
        """Setup/compile time before iteration 1 counts against the time
        budget: initialize() arms the clock when fit() starts, so the
        first terminate() check already sees the elapsed setup time."""
        from deeplearning4j_tpu.train import earlystopping as es

        (DataSetLossCalculator, EarlyStoppingConfiguration,
         EarlyStoppingTrainer, MaxEpochsTerminationCondition) = \
            self._es_parts()

        clock = [0.0]

        def fake_monotonic():
            clock[0] += 100.0  # every look at the clock jumps 100s
            return clock[0]

        monkeypatch.setattr(es.time, "monotonic", fake_monotonic)
        net = _net()
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(
                ExistingDataSetIterator(_batches(1))),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(1)],
            iteration_termination_conditions=[
                es.MaxTimeIterationTerminationCondition(10.0)],
        )
        result = EarlyStoppingTrainer(
            cfg, net, ExistingDataSetIterator(_batches(1))).fit()
        # with a lazily-armed clock the first check would read 0s elapsed
        # and the run would end via MaxEpochs instead
        assert result.termination_reason == "IterationTerminationCondition"
        assert "MaxTime" in result.termination_details


class TestCliWiring:
    def test_fault_flags_reach_the_model(self, tmp_path, monkeypatch, capsys):
        from deeplearning4j_tpu import cli

        built = {}

        def fake_dataset(name, batch_size, num_examples):
            return ExistingDataSetIterator(_batches(2)), N_OUT

        def fake_model(name, num_classes, dataset, compute_dtype=None,
                       remat_policy=None):
            built["net"] = _net()
            return built["net"]

        monkeypatch.setattr(cli, "build_dataset", fake_dataset)
        monkeypatch.setattr(cli, "build_model", fake_model)
        ckdir = str(tmp_path / "ck")
        rc = cli.main([
            "--model", "tiny", "--epochs", "2",
            "--skip-nonfinite", "--max-bad-steps", "5",
            "--checkpoint-dir", ckdir, "--keep-last", "2",
        ])
        assert rc == 0
        pol = built["net"].conf.global_conf.fault_policy
        assert pol is not None and pol.skip_nonfinite
        assert pol.max_consecutive_bad_steps == 5
        assert built["net"].bad_step_count == 0
        cks = [f for f in os.listdir(ckdir) if f.endswith(".zip")]
        assert 1 <= len(cks) <= 2  # epoch saves under keep-last-2

        # --resume restores the newest valid checkpoint
        rc = cli.main([
            "--model", "tiny", "--epochs", "1",
            "--checkpoint-dir", ckdir, "--resume",
        ])
        assert rc == 0
        assert "resumed from" in capsys.readouterr().out
