"""Observability tests: StatsListener → storage → dashboard (VERDICT r2
item 5 done criteria: train with the stats listener, open the HTML
report, see score/update:param-ratio/memory curves at
reportingFrequency). Mirrors reference ui-model tests (headless render).
"""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    EvaluationTools,
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsListener,
    UIServer,
    render_dashboard,
)
from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.updaters import Adam


def _net():
    conf = (
        NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
        .weight_init("xavier").list()
        .layer(DenseLayer(n_out=12, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(5)).build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


class TestStatsListener:
    def test_records_collected_at_frequency(self):
        storage = InMemoryStatsStorage()
        net = _net()
        net.add_listeners(StatsListener(storage, reporting_frequency=2,
                                        session_id="s1"))
        net.fit(_data(), epochs=2, batch_size=16)  # 6 iters/epoch → 12
        records = storage.get_records("s1")
        kinds = [r["kind"] for r in records]
        assert kinds.count("init") == 1
        updates = [r for r in records if r["kind"] == "update"]
        # iterations 1, 2, 4, 6, 8, 10, 12
        assert [r["iteration"] for r in updates] == [1, 2, 4, 6, 8, 10, 12]
        for r in updates:
            assert np.isfinite(r["score"])
            assert r["memory_rss_mb"] > 0
            assert "parameters" in r and "0_W" in r["parameters"]
            p = r["parameters"]["0_W"]
            assert {"mean", "stdev", "mean_magnitude"} <= set(p)
            assert "histogram" in p
        # update stats exist from the second report onward
        assert "updates" in updates[1]
        assert "update_param_ratio" in updates[1]
        ratios = updates[1]["update_param_ratio"]
        assert all(v >= 0 for v in ratios.values())

    def test_file_storage_roundtrip(self, tmp_path):
        path = str(tmp_path / "stats.jsonl")
        storage = FileStatsStorage(path)
        net = _net()
        net.add_listeners(StatsListener(storage, session_id="fs"))
        net.fit(_data(), epochs=1, batch_size=32)
        # JSONL on disk, one record per line
        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert len(lines) == len(storage.get_records("fs"))
        # fresh reader sees the same session
        storage2 = FileStatsStorage(path)
        assert storage2.list_session_ids() == ["fs"]

    def test_listener_notification(self):
        storage = InMemoryStatsStorage()
        seen = []
        storage.register_stats_storage_listener(seen.append)
        net = _net()
        net.add_listeners(StatsListener(storage, session_id="n"))
        net.fit(_data(), epochs=1, batch_size=48)
        assert len(seen) == len(storage.get_records("n"))


class TestDashboard:
    def test_render_contains_curves(self, tmp_path):
        storage = InMemoryStatsStorage()
        net = _net()
        net.add_listeners(StatsListener(storage, session_id="d1"))
        net.fit(_data(), epochs=2, batch_size=16)
        out = str(tmp_path / "dash.html")
        html_doc = render_dashboard(storage, path=out)
        assert os.path.exists(out)
        for needle in ("Score vs Iteration", "Update : Parameter ratio",
                       "Host memory", "<svg", "d1"):
            assert needle in html_doc

    def test_uiserver_attach_render(self, tmp_path):
        storage = InMemoryStatsStorage()
        net = _net()
        net.add_listeners(StatsListener(storage, session_id="u1"))
        net.fit(_data(), epochs=1, batch_size=24)
        srv = UIServer.get_instance()
        srv.attach(storage)
        out = str(tmp_path / "srv.html")
        doc = srv.render(out)
        assert "u1" in doc
        srv.detach(storage)

    def test_live_server_shows_training_progress(self):
        """VERDICT r3 item 3 done-criterion: fetch the dashboard twice
        DURING training and see the iteration count advance (reference
        PlayUIServer serves a polling UI while the run is live)."""
        import re
        import urllib.request

        storage = InMemoryStatsStorage()
        net = _net()
        net.add_listeners(StatsListener(storage, reporting_frequency=1,
                                        session_id="live1"))
        srv = UIServer()  # private instance: don't leak into other tests
        srv.attach(storage)
        srv.start(port=0)
        try:
            url = f"http://127.0.0.1:{srv.port}"
            ds = _data()

            def fetch(path="/train"):
                with urllib.request.urlopen(url + path, timeout=10) as r:
                    return r.read().decode()

            def n_records(doc):
                m = re.search(r"records: (\d+)", doc)
                assert m, "dashboard page missing records count"
                return int(m.group(1))

            net.fit(ds, epochs=1, batch_size=16)  # 6 iterations
            page1 = fetch()
            assert "live1" in page1 and "Score vs Iteration" in page1
            assert 'http-equiv="refresh"' in page1  # browser auto-polls
            net.fit(ds, epochs=1, batch_size=16)  # 6 more
            page2 = fetch()
            assert n_records(page2) > n_records(page1)
            # route table parity: /sessions JSON + per-session page
            assert json.loads(fetch("/sessions")) == ["live1"]
            assert "live1" in fetch("/train/live1")
            # layer drill-down (TrainModule model-tab view): overview
            # links to per-layer pages with that layer's curves
            assert "/train/live1/layer/0_W" in page2
            layer_page = fetch("/train/live1/layer/0_W")
            assert "0_W parameter mean / stdev" in layer_page
            assert "update : parameter ratio" in layer_page
            assert "parameter distribution" in layer_page  # histogram
            assert "<svg" in layer_page
            # remote-listener endpoint feeds the attached storage
            req = urllib.request.Request(
                url + "/stats",
                data=json.dumps({"session_id": "remote-s", "kind": "update",
                                 "iteration": 1, "score": 1.0,
                                 "memory_rss_mb": 1.0}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
            assert "remote-s" in storage.list_session_ids()
        finally:
            srv.stop()
        assert srv.port is None  # stopped cleanly

    def test_computation_graph_supported(self):
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,  # noqa: F401
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (
            NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
            .graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("o", OutputLayer(n_out=3, activation="softmax",
                                        loss="mcxent"), "d")
            .set_outputs("o").set_input_types(InputType.feed_forward(5))
            .build()
        )
        net = ComputationGraph(conf).init()
        storage = InMemoryStatsStorage()
        net.add_listeners(StatsListener(storage, session_id="cg"))
        net.fit(_data(), epochs=1, batch_size=32)
        updates = [r for r in storage.get_records("cg") if r["kind"] == "update"]
        assert updates
        assert any(k.startswith("d_") for k in updates[0]["parameters"])


class TestEvaluationTools:
    def test_roc_html_export(self, tmp_path):
        from deeplearning4j_tpu.evaluation import ROC

        rng = np.random.default_rng(3)
        labels = rng.integers(0, 2, 200)
        # informative probabilities
        probs = np.clip(labels * 0.6 + rng.random(200) * 0.4, 0, 1)
        roc = ROC()
        roc.eval(np.eye(2)[labels], np.stack([1 - probs, probs], 1))
        p = str(tmp_path / "roc.html")
        EvaluationTools.export_roc_charts_to_html_file(roc, p)
        doc = open(p).read()
        assert "AUC=" in doc and "<svg" in doc

    def test_calibration_html_export(self, tmp_path):
        from deeplearning4j_tpu.evaluation import EvaluationCalibration

        rng = np.random.default_rng(4)
        labels = rng.integers(0, 2, 300)
        probs = np.clip(labels * 0.5 + rng.random(300) * 0.5, 0, 1)
        cal = EvaluationCalibration()
        cal.eval(np.eye(2)[labels], np.stack([1 - probs, probs], 1))
        p = str(tmp_path / "cal.html")
        EvaluationTools.export_calibration_to_html_file(cal, p, cls=1)
        assert "ECE=" in open(p).read()


class TestRemoteStats:
    def test_train_posts_to_remote_receiver_and_dashboard_renders(self, tmp_path):
        """Trainer → RemoteUIStatsStorageRouter → HTTP → receiver storage
        → dashboard (reference RemoteUIStatsStorageRouter +
        RemoteReceiverModule flow)."""
        from deeplearning4j_tpu.ui import (
            RemoteStatsReceiver,
            RemoteUIStatsStorageRouter,
        )

        backing = InMemoryStatsStorage()
        recv = RemoteStatsReceiver(backing, port=0).start()
        try:
            router = RemoteUIStatsStorageRouter(
                f"http://127.0.0.1:{recv.port}"
            )
            net = _net()
            net.add_listeners(StatsListener(router, session_id="remote1"))
            net.fit(_data(), epochs=1, batch_size=24)
            router.flush()
            records = backing.get_records("remote1")
            assert any(r["kind"] == "update" for r in records)
            out = str(tmp_path / "remote.html")
            doc = render_dashboard(backing, path=out)
            assert "remote1" in doc
            assert router.dropped == 0
        finally:
            router.shutdown()
            recv.stop()

    def test_router_counts_drops_when_receiver_down(self):
        from deeplearning4j_tpu.ui import RemoteUIStatsStorageRouter

        router = RemoteUIStatsStorageRouter(
            "http://127.0.0.1:9", async_post=False, max_retries=1,
            timeout=0.5,
        )
        router.put_record({"kind": "update", "session_id": "x",
                           "worker_id": "w"})
        assert router.dropped == 1


class TestProfilerListener:
    def test_trace_captured(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import ProfilerListener

        log_dir = str(tmp_path / "trace")
        net = _net()
        lst = ProfilerListener(log_dir, start_iteration=2, num_iterations=2)
        net.add_listeners(lst)
        net.fit(_data(), epochs=2, batch_size=16)
        assert lst.completed
        # xprof writes plugins/profile/<run>/ under the log dir
        found = []
        for root, _dirs, files in os.walk(log_dir):
            found.extend(files)
        assert found, "no trace files written"


class TestUIComponents:
    """Component DSL (reference deeplearning4j-ui-components:
    chart/table/text/div/accordion + styles, JSON wire format)."""

    def _sample_components(self):
        from deeplearning4j_tpu.ui import (
            ChartHistogram, ChartHorizontalBar, ChartLine, ChartScatter,
            ChartStackedArea, ChartTimeline, ComponentDiv, ComponentTable,
            ComponentText, DecoratorAccordion, StyleChart, StyleText,
        )
        line = ChartLine("loss", StyleChart(width=400, height=200))
        line.add_series("train", [0, 1, 2, 3], [1.0, 0.6, 0.4, 0.3])
        line.add_series("val", [0, 1, 2, 3], [1.1, 0.8, 0.6, 0.55])
        scatter = ChartScatter("embedding").add_series("pts", [0, 1, 2], [2, 1, 3])
        hist = (ChartHistogram("weights").add_bin(-1, -0.5, 3)
                .add_bin(-0.5, 0, 10).add_bin(0, 0.5, 12).add_bin(0.5, 1, 2))
        bars = (ChartHorizontalBar("per-layer time (ms)")
                .add_bar("conv1", 4.2).add_bar("dense", 1.1))
        area = (ChartStackedArea("memory").set_x([0, 1, 2])
                .add_series("params", [10, 10, 10]).add_series("acts", [5, 9, 7]))
        tl = ChartTimeline("phases").add_lane("worker0", [
            {"start": 0.0, "end": 1.5, "label": "etl"},
            {"start": 1.5, "end": 4.0, "label": "fit"},
        ])
        table = ComponentTable(header=["layer", "params"],
                               content=[["conv1", "9408"], ["dense", "4096"]],
                               title="model")
        text = ComponentText("Training report", StyleText(underline=True))
        acc = DecoratorAccordion("details", default_collapsed=False,
                                 children=[table])
        div = ComponentDiv(children=[text, line])
        return [div, scatter, hist, bars, area, tl, acc]

    def test_json_round_trip_every_component(self):
        from deeplearning4j_tpu.ui import Component

        for comp in self._sample_components():
            js = comp.to_json()
            back = Component.from_json(js)
            assert type(back) is type(comp)
            assert back.to_dict() == comp.to_dict()

    def test_render_page_standalone_html(self, tmp_path):
        from deeplearning4j_tpu.ui import render_page, save_page

        comps = self._sample_components()
        html_text = render_page(comps, title="Round-trip report")
        assert html_text.startswith("<!DOCTYPE html>")
        assert html_text.count("<svg") >= 5
        assert "polyline" in html_text        # line chart marks
        assert "circle" in html_text          # scatter marks
        assert "<table" in html_text and "conv1" in html_text
        assert "<details open" in html_text   # expanded accordion
        p = str(tmp_path / "report.html")
        save_page(comps, p, title="t")
        assert os.path.getsize(p) > 1000

    def test_restored_component_renders_identically(self):
        from deeplearning4j_tpu.ui import Component

        for comp in self._sample_components():
            back = Component.from_json(comp.to_json())
            assert back.render_html() == comp.render_html()

    def test_series_length_mismatch_raises(self):
        from deeplearning4j_tpu.ui import ChartLine, ChartStackedArea

        with pytest.raises(ValueError):
            ChartLine("x").add_series("s", [1, 2], [1])
        with pytest.raises(ValueError):
            ChartStackedArea("x").set_x([1, 2]).add_series("s", [1])


class TestConvolutionalListener:
    """reference ConvolutionalIterationListener: activation-grid images of
    conv layers at a fixed frequency."""

    def test_png_writer_valid_signature_and_size(self, tmp_path):
        from deeplearning4j_tpu.ui import write_png_gray

        img = (np.arange(20 * 30) % 256).astype(np.uint8).reshape(20, 30)
        p = write_png_gray(str(tmp_path / "x.png"), img)
        data = open(p, "rb").read()
        assert data[:8] == b"\x89PNG\r\n\x1a\n"
        w, h = np.frombuffer(data[16:24], ">u4")
        assert (w, h) == (30, 20)

    def test_activation_grid_tiles_channels(self):
        from deeplearning4j_tpu.ui import activation_grid

        act = np.random.randn(8, 8, 9).astype(np.float32)
        grid = activation_grid(act)
        assert grid.dtype == np.uint8
        # 9 channels -> 3x3 grid of 8x8 tiles + 1px padding
        assert grid.shape == (3 * 9 + 1, 3 * 9 + 1)

    def test_listener_writes_grids_during_training(self, tmp_path):
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer, OutputLayer,
        )
        from deeplearning4j_tpu.ui import ConvolutionalIterationListener

        conf = (
            NeuralNetConfiguration.builder().seed(7)
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3,
                                    convolution_mode="same"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        probe = np.random.randn(2, 8, 8, 1).astype(np.float32)
        lst = ConvolutionalIterationListener(probe, str(tmp_path), frequency=2)
        net.listeners.append(lst)
        x = np.random.randn(8, 8, 8, 1).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.random.randint(0, 3, 8)]
        net.fit(DataSet(x, y), epochs=2, batch_size=4)
        pngs = [f for f in os.listdir(tmp_path) if f.endswith(".png")]
        assert pngs, "no activation grids written"
        idx = os.path.join(tmp_path, "index.html")
        assert os.path.exists(idx)
        assert "<img" in open(idx).read()


class TestComponentCompat:
    def test_chartline_pre_logy_payload_renders(self):
        """Payloads serialized before the log_y field existed must still
        deserialize and render."""
        import json as _json

        from deeplearning4j_tpu.ui import ChartLine, Component

        d = ChartLine("t").add_series("s", [0, 1], [1.0, 2.0]).to_dict()
        del d["log_y"]
        back = Component.from_dict(d)
        html_text = back.render_html()
        assert "polyline" in html_text
        # and round-trips again
        assert _json.loads(back.to_json())["log_y"] is False

    def test_legend_wraps_many_series(self):
        from deeplearning4j_tpu.ui import ChartLine

        c = ChartLine("many")
        for i in range(12):
            c.add_series(f"layer_{i}_gamma", [0, 1], [i, i + 1])
        html_text = c.render_html()
        # wrapped legend rows: at least one legend rect below the first row
        import re

        ys = {m.group(1) for m in
              re.finditer(r'<rect x="[\d.]+" y="(\d+[\d.]*)" width="9"',
                          html_text)}
        assert len(ys) >= 2, f"legend did not wrap: rows at {ys}"

    def test_dashboard_no_finite_data_placeholder(self):
        from deeplearning4j_tpu.ui.dashboard import _line

        out = _line({"score": [(0, float("nan")), (1, float("inf"))]}, "S")
        assert "no finite data" in out


class TestLegendPlacement:
    def test_wrapped_rows_land_below_plot_not_over_data(self):
        import re

        from deeplearning4j_tpu.ui import ChartLine, StyleChart

        st = StyleChart(width=400, height=200)
        c = ChartLine("many", st)
        for i in range(10):
            c.add_series(f"layer_{i}_gamma_param", [0, 1], [i, i + 1])
        html_text = c.render_html()
        plot_top = st.margin_top
        plot_bottom = st.height - st.margin_bottom
        rows = sorted({float(m.group(1)) for m in re.finditer(
            r'<rect x="[\d.]+" y="(-?[\d.]+)" width="9"', html_text)})
        assert len(rows) >= 2, "legend did not wrap"
        for y in rows:
            inside_plot = plot_top < y < plot_bottom
            assert not inside_plot, f"legend row at y={y} occludes the plot"
        # canvas extended to hold the overflow rows
        h = float(re.search(r'viewBox="0 0 [\d.]+ ([\d.]+)"', html_text).group(1))
        assert h > st.height


class TestIntrospectionHooks:
    """on_forward_pass / on_gradient_calculation / on_backward_pass
    (reference TrainingListener.java:23-71; SURVEY §7 hard-part 1's
    introspection mode)."""

    class _Capture(TrainingListener):
        def __init__(self):
            self.acts, self.grads, self.bwd = [], [], 0

        def on_forward_pass(self, model, activations):
            self.acts.append(activations)

        def on_gradient_calculation(self, model, gradients):
            self.grads.append(gradients)

        def on_backward_pass(self, model):
            self.bwd += 1

    @staticmethod
    def _mln(listeners=()):
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import Sgd

        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        n = MultiLayerNetwork(conf).init()
        n.listeners = list(listeners)
        return n

    @staticmethod
    def _data():
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        from deeplearning4j_tpu.data import DataSet

        return DataSet(x, y)

    def test_mln_hooks_fire_with_correct_shapes(self):
        cap = self._Capture()
        net = self._mln([cap])
        net.fit(self._data(), epochs=2, batch_size=4)  # 4 iterations
        assert len(cap.acts) == 4 and len(cap.grads) == 4 and cap.bwd == 4
        assert len(cap.acts[0]) == 2
        assert cap.acts[0][0].shape == (4, 6)
        assert cap.acts[0][1].shape == (4, 3)
        assert set(cap.grads[0][0]) == {"W", "b"}
        assert cap.grads[0][0]["W"].shape == (4, 6)

    def test_attaching_listener_does_not_change_training(self):
        """The introspection pass reuses the step's rng — identical
        trajectories with and without the listener."""
        ds = self._data()
        n1 = self._mln([self._Capture()])
        n1.fit(ds, epochs=2, batch_size=4)
        n2 = self._mln()
        n2.fit(ds, epochs=2, batch_size=4)
        for p1, p2 in zip(n1.params_, n2.params_):
            for k in p1:
                np.testing.assert_array_equal(np.asarray(p1[k]),
                                              np.asarray(p2[k]))

    def test_cg_hooks_fire(self):
        from deeplearning4j_tpu.data import DataSet
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.updaters import Sgd

        conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=5, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3)).build())
        net = ComputationGraph(conf).init()
        cap = self._Capture()
        net.listeners = [cap]
        rng = np.random.default_rng(1)
        x = rng.standard_normal((6, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
        net.fit(DataSet(x, y), epochs=1, batch_size=6)
        assert len(cap.acts) == 1 and len(cap.grads) == 1
        assert isinstance(cap.acts[0], dict) and "d" in cap.acts[0]
        assert cap.acts[0]["d"].shape == (6, 5)
        assert set(cap.grads[0]["d"]) == {"W", "b"}

    def test_stats_listener_collects_gradients_and_activations(self):
        storage = InMemoryStatsStorage()
        lst = StatsListener(storage, reporting_frequency=2,
                            collect_gradients=True,
                            collect_activations=True)
        net = self._mln([lst])
        net.fit(self._data(), epochs=3, batch_size=4)  # 6 iterations
        updates = [r for r in storage.get_records(lst.session_id)
                   if r["kind"] == "update"]
        assert updates, "no update records"
        with_grads = [r for r in updates if "gradients" in r]
        assert with_grads, "no gradient stats collected"
        g = next(iter(with_grads[0]["gradients"].values()))
        assert {"mean", "stdev", "mean_magnitude"} <= set(g)
        assert any("activations" in r for r in updates)

    def test_frequency_gates_introspection_pass(self):
        """needs_introspection: the extra fwd+grad pass only runs on
        reporting iterations."""
        storage = InMemoryStatsStorage()
        lst = StatsListener(storage, reporting_frequency=3,
                            collect_gradients=True)
        calls = {"n": 0}
        orig = lst._on_gradient_calculation

        def counting(model, grads):
            calls["n"] += 1
            return orig(model, grads)

        lst.on_gradient_calculation = counting
        net = self._mln([lst])
        net.fit(self._data(), epochs=3, batch_size=4)  # 6 iterations
        # iterations 1..6 -> introspected at next_iteration in {1, 3, 6}
        assert calls["n"] == 3, calls["n"]

    def test_dashboard_renders_gradient_and_activation_charts(self):
        storage = InMemoryStatsStorage()
        lst = StatsListener(storage, reporting_frequency=1,
                            collect_gradients=True,
                            collect_activations=True)
        net = self._mln([lst])
        net.fit(self._data(), epochs=1, batch_size=4)
        html_doc = render_dashboard(storage)
        assert "Gradient mean magnitude" in html_doc
        assert "Activation stdev" in html_doc

    def test_per_listener_delivery_gating(self):
        """An always-on introspection listener must not cause a sampled
        StatsListener to receive (and host-copy) hooks off-frequency."""
        storage = InMemoryStatsStorage()
        sampled = StatsListener(storage, reporting_frequency=3,
                                collect_gradients=True)
        s_calls = {"n": 0}
        orig = sampled._on_gradient_calculation

        def counting(model, grads):
            s_calls["n"] += 1
            return orig(model, grads)

        sampled.on_gradient_calculation = counting

        class AlwaysOn(TrainingListener):
            def __init__(self):
                self.n = 0

            def on_gradient_calculation(self, model, gradients):
                self.n += 1

        always = AlwaysOn()
        net = self._mln([sampled, always])
        net.fit(self._data(), epochs=3, batch_size=4)  # 6 iterations
        assert always.n == 6
        assert s_calls["n"] == 3  # {1, 3, 6} only
