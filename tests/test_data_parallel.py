"""Data pipeline + parallelism tests.

Parity model: reference ``ParallelWrapperMainTest`` / parameter-averaging
vs single-machine comparison (``TestCompareParameterAveragingSparkVs
SingleMachine.java``, SURVEY.md §4.5) — here DP-vs-single-device must agree
because SPMD all-reduce of a mean IS the single-device gradient.
"""

import os
import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ExistingDataSetIterator, ListDataSetIterator
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    BenchmarkDataSetIterator,
    EarlyTerminationDataSetIterator,
    GeneratorDataSetIterator,
    MultipleEpochsIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.data.mnist import IrisDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelInference, ParallelWrapper, TrainingMesh
from deeplearning4j_tpu.updaters import Sgd


def _net(seed=3):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1))
        .list()
        .layer(DenseLayer(n_out=8, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _blobs(n=64, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((3, 4)) * 3
    cls = rng.integers(0, 3, n)
    x = (centers[cls] + rng.standard_normal((n, 4)) * 0.3).astype(np.float32)
    return DataSet(x, np.eye(3, dtype=np.float32)[cls])


class TestIterators:
    def test_async_matches_sync(self):
        ds = _blobs(50)
        sync = ListDataSetIterator(ds, 16)
        async_it = AsyncDataSetIterator(ListDataSetIterator(ds, 16), 2)
        a = [d.features for d in sync]
        b = [d.features for d in async_it]
        assert len(a) == len(b) == 4
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_async_propagates_worker_errors(self):
        class Bad(ListDataSetIterator):
            def next(self):
                if self._pos >= 32:
                    raise RuntimeError("ETL failed")
                return super().next()

        it = AsyncDataSetIterator(Bad(_blobs(64), 16), 2)
        seen = 0
        with pytest.raises(RuntimeError, match="ETL failed"):
            for _ in it:
                seen += 1
        assert seen == 2

    def test_early_termination(self):
        it = EarlyTerminationDataSetIterator(ListDataSetIterator(_blobs(64), 8), 3)
        assert sum(1 for _ in it) == 3
        it.reset()
        assert sum(1 for _ in it) == 3

    def test_multiple_epochs(self):
        inner = TestDataSetIterator(ListDataSetIterator(_blobs(32), 16))
        it = MultipleEpochsIterator(inner, 3)
        assert sum(1 for _ in it) == 6
        assert inner.reset_count == 2

    def test_benchmark_iterator_replays(self):
        it = BenchmarkDataSetIterator.from_shapes((4, 3), (4, 2), 5)
        batches = list(it)
        assert len(batches) == 5
        np.testing.assert_array_equal(batches[0].features, batches[4].features)

    def test_generator_iterator(self):
        it = GeneratorDataSetIterator(lambda: (d for d in _blobs(32).batch_by(8)))
        assert sum(1 for _ in it) == 4
        it.reset()
        assert sum(1 for _ in it) == 4


class TestMnistIris:
    def test_mnist_shapes_and_determinism(self):
        a = MnistDataSetIterator(32, train=True, num_examples=64, seed=5)
        b = MnistDataSetIterator(32, train=True, num_examples=64, seed=5)
        da, db = a.next(), b.next()
        np.testing.assert_array_equal(da.features, db.features)
        assert da.features.shape == (32, 28, 28, 1)
        assert da.labels.shape == (32, 10)
        assert 0.0 <= da.features.min() and da.features.max() <= 1.0

    def test_train_test_disjoint_generation(self):
        tr = MnistDataSetIterator(64, train=True, num_examples=64, shuffle=False)
        te = MnistDataSetIterator(64, train=False, num_examples=64, shuffle=False)
        assert not np.array_equal(tr.next().features, te.next().features)

    def test_iris(self):
        it = IrisDataSetIterator(150)
        ds = it.next()
        assert ds.features.shape == (150, 4)
        np.testing.assert_array_equal(ds.labels.sum(axis=0), [50, 50, 50])


class TestNormalizers:
    def test_standardize_roundtrip(self):
        ds = _blobs(100)
        orig = ds.features.copy()
        n = NormalizerStandardize()
        n.fit(ds)
        n.transform(ds)
        assert abs(ds.features.mean()) < 1e-5
        assert abs(ds.features.std() - 1.0) < 0.05
        n.revert(ds)
        np.testing.assert_allclose(ds.features, orig, atol=1e-4)

    def test_minmax(self):
        ds = _blobs(50)
        n = NormalizerMinMaxScaler(0, 1)
        n.fit(ds)
        n.transform(ds)
        assert ds.features.min() >= -1e-6 and ds.features.max() <= 1 + 1e-6

    def test_image_scaler(self):
        ds = DataSet(np.full((2, 4, 4, 1), 255.0, np.float32))
        ImagePreProcessingScaler().transform(ds)
        np.testing.assert_allclose(ds.features, 1.0)

    def test_serde(self):
        ds = _blobs(50)
        n = NormalizerStandardize()
        n.fit(ds)
        from deeplearning4j_tpu.data.normalizers import Normalizer

        n2 = Normalizer.from_dict(n.to_dict())
        np.testing.assert_allclose(n.mean, n2.mean)


class TestParallel:
    def test_dp_matches_single_device(self):
        """SPMD all-reduce of the mean gradient == single-device training."""
        ds = _blobs(64)
        it1 = ListDataSetIterator(ds, 32)
        it2 = ListDataSetIterator(ds, 32)
        single = _net(seed=11)
        dp = _net(seed=11)
        single.fit(it1, epochs=3)
        mesh = TrainingMesh(data=8)
        ParallelWrapper(dp, mesh=mesh).fit(it2, epochs=3)
        np.testing.assert_allclose(
            single.params_flat(), dp.params_flat(), rtol=2e-4, atol=1e-5
        )

    def test_mesh_shapes(self):
        mesh = TrainingMesh(data=4, model=2)
        assert mesh.shape == {"data": 4, "model": 2, "pipe": 1, "seq": 1,
                              "expert": 1}
        with pytest.raises(ValueError):
            TrainingMesh(data=5)

    def test_parallel_inference_coalesces(self):
        net = _net()
        ds = _blobs(64)
        pi = ParallelInference.builder(net).batch_limit(64).build()
        results = {}

        def call(i):
            results[i] = pi.output(ds.features[i * 8 : (i + 1) * 8])

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ref = net.output(ds.features)
        for i in range(8):
            np.testing.assert_allclose(results[i], ref[i * 8 : (i + 1) * 8], atol=1e-6)
        pi.shutdown()
        with pytest.raises(RuntimeError):
            pi.output(ds.features[:8])

    def test_parallel_inference_modes_output_equality(self):
        """sequential / batched / inplace must all produce the direct
        model output (reference InferenceMode surface; INPLACE is the
        later-era third mode)."""
        net = _net()
        ds = _blobs(32)
        ref = np.asarray(net.output(ds.features))
        for mode in ("sequential", "batched", "inplace"):
            pi = (ParallelInference.builder(net).inference_mode(mode)
                  .workers(3).build())
            out = np.asarray(pi.output(ds.features))
            np.testing.assert_allclose(out, ref, atol=1e-6)
            pi.shutdown()

    def test_parallel_inference_inplace_concurrent(self):
        """inplace: concurrent callers round-robin over model replicas;
        every request gets its own correct result."""
        net = _net()
        ds = _blobs(64)
        pi = (ParallelInference.builder(net).inference_mode("inplace")
              .workers(4).build())
        assert len(pi._replicas) == 4
        ref = np.asarray(net.output(ds.features))
        results = {}

        def call(i):
            results[i] = pi.output(ds.features[i * 8: (i + 1) * 8])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            np.testing.assert_allclose(results[i], ref[i * 8: (i + 1) * 8],
                                       atol=1e-6)
        pi.shutdown()
        with pytest.raises(RuntimeError):
            pi.output(ds.features[:8])

    def test_parallel_inference_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="inference mode"):
            ParallelInference(_net(), mode="spooky")

    def test_wrapper_tbptt_2d_data_falls_through_to_standard(self):
        # tBPTT configs are supported since round 3 (tests/test_parity_tail
        # covers the sharded chunk path); 2D batches just train normally
        conf = (
            NeuralNetConfiguration.builder()
            .list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .backprop_type("tbptt")
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ParallelWrapper(net, mesh=TrainingMesh(data=8)).fit(
            ListDataSetIterator(_blobs(16), 8)
        )
        assert np.isfinite(float(net.score_))


class TestZoo:
    @pytest.mark.slow
    def test_lenet_instantiation(self):
        from deeplearning4j_tpu.models import LeNet

        net = LeNet(num_classes=10).init()
        out = net.output(np.zeros((2, 28, 28, 1), np.float32))
        assert out.shape == (2, 10)

    @pytest.mark.slow
    def test_simplecnn_instantiation(self):
        from deeplearning4j_tpu.models import SimpleCNN

        net = SimpleCNN(num_classes=5, height=48, width=48, channels=3).init()
        out = net.output(np.zeros((2, 48, 48, 3), np.float32))
        assert out.shape == (2, 5)


class TestIteratorPreProcessor:
    """reference DataSetIterator.setPreProcessor: every iterator applies
    the attached normalizer to each emitted batch, wrappers forward it,
    and replayed DataSets are never normalized twice."""

    def _base(self):
        x = np.array([[-1.0], [1.0], [3.0], [5.0]], np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 0, 1, 1]]
        return DataSet(x, y)

    def test_list_iterator_applies_normalizer(self):
        ds = self._base()
        norm = NormalizerStandardize()
        norm.fit(ds)
        it = ListDataSetIterator(ds, 4)
        it.set_pre_processor(norm)
        out = it.next()
        np.testing.assert_allclose(out.features.mean(), 0.0, atol=1e-6)
        # source DataSet untouched
        np.testing.assert_allclose(ds.features[:, 0], [-1, 1, 3, 5])

    def test_no_double_normalization_across_epochs(self):
        ds = self._base()
        norm = NormalizerStandardize()
        norm.fit(ds)
        it = ExistingDataSetIterator([ds])
        it.set_pre_processor(norm)
        first = it.next().features.copy()
        it.reset()
        second = it.next().features.copy()
        np.testing.assert_allclose(first, second)

    def test_wrappers_forward_to_leaf(self):
        ds = self._base()
        norm = NormalizerStandardize()
        norm.fit(ds)
        inner = ListDataSetIterator(ds, 2)
        it = MultipleEpochsIterator(EarlyTerminationDataSetIterator(inner, 10), 2)
        it.set_pre_processor(norm)
        batches = [b.features.copy() for b in it]
        assert len(batches) == 4  # 2 epochs x 2 batches
        np.testing.assert_allclose(batches[0], batches[2])  # epoch replays equal
        np.testing.assert_allclose(np.concatenate(batches[:2]).mean(), 0.0,
                                   atol=1e-6)

    def test_record_reader_iterator_applies_normalizer(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )

        p = tmp_path / "d.csv"
        p.write_text("".join(f"{v},{k}\n" for v, k in
                             [(-1, 0), (1, 0), (3, 1), (5, 1)]))
        it = RecordReaderDataSetIterator(CSVRecordReader(str(p)), 4,
                                         label_index=1, num_possible_labels=2)
        norm = NormalizerStandardize()
        norm.fit(it)
        it.reset()
        it.set_pre_processor(norm)
        out = it.next()
        np.testing.assert_allclose(out.features.mean(), 0.0, atol=1e-6)


class TestIteratorCombinatorTail:
    """Remaining reference utility-iterators (SURVEY §2.2):
    IteratorDataSetIterator, Doubles/Floats, Reconstruction, AsyncShield,
    Splitter, JointParallel, FileDataSetIterator + DataSet.save/load."""

    def test_iterator_rebatching(self):
        from deeplearning4j_tpu.data import IteratorDataSetIterator

        smalls = _blobs(10).batch_by(2)  # five 2-example DataSets
        it = IteratorDataSetIterator(smalls, batch_size=4)
        sizes = [d.num_examples() for d in it]
        assert sizes == [4, 4, 2]
        it.reset()
        assert [d.num_examples() for d in it] == [4, 4, 2]
        # one-shot generator input: reset must still replay (materialized)
        gen_it = IteratorDataSetIterator((d for d in _blobs(8).batch_by(2)), 4)
        assert [d.num_examples() for d in gen_it] == [4, 4]
        gen_it.reset()
        assert [d.num_examples() for d in gen_it] == [4, 4]

    def test_doubles_floats(self):
        from deeplearning4j_tpu.data import (
            DoublesDataSetIterator, FloatsDataSetIterator,
        )

        pairs = [([1.0, 2.0], [1.0, 0.0]), ([3.0, 4.0], [0.0, 1.0]),
                 ([5.0, 6.0], [1.0, 0.0])]
        d_it = DoublesDataSetIterator(pairs, 2)
        first = d_it.next()
        assert first.features.dtype == np.float64
        assert first.features.shape == (2, 2)
        f_it = FloatsDataSetIterator(pairs, 3)
        assert f_it.next().features.dtype == np.float32

    def test_reconstruction(self):
        from deeplearning4j_tpu.data import ReconstructionDataSetIterator

        it = ReconstructionDataSetIterator(ListDataSetIterator(_blobs(8), 8))
        d = it.next()
        np.testing.assert_array_equal(d.features, d.labels)

    def test_async_shield(self):
        from deeplearning4j_tpu.data import AsyncShieldDataSetIterator

        it = AsyncShieldDataSetIterator(ListDataSetIterator(_blobs(8), 4))
        assert not it.async_supported()
        assert sum(1 for _ in it) == 2

    def test_splitter(self):
        from deeplearning4j_tpu.data import DataSetIteratorSplitter

        inner = ListDataSetIterator(_blobs(80), 8)  # 10 batches
        sp = DataSetIteratorSplitter(inner, total_batches=10, ratio=0.7)
        train_it = sp.get_train_iterator()
        train = [d.features.copy() for d in train_it]
        test_it = sp.get_test_iterator()
        test = [d.features.copy() for d in test_it]
        assert len(train) == 7 and len(test) == 3
        # no leakage: test batches disjoint from every train batch
        for t in test:
            assert not any(np.array_equal(t, tr) for tr in train)
        # views survive reset() (the fit/evaluate loops reset per epoch)
        train_it.reset()
        test_it.reset()
        train2 = [d.features.copy() for d in train_it]
        test2 = [d.features.copy() for d in test_it]
        np.testing.assert_array_equal(train2[0], train[0])
        np.testing.assert_array_equal(test2[0], test[0])
        with pytest.raises(ValueError):
            DataSetIteratorSplitter(inner, 10, 1.5)

    def test_joint_parallel_modes(self):
        from deeplearning4j_tpu.data import JointParallelDataSetIterator

        def srcs(n1, n2):
            return (ListDataSetIterator(_blobs(n1 * 4, seed=1), 4),
                    ListDataSetIterator(_blobs(n2 * 4, seed=2), 4))

        # stop the moment ANY source drains, regardless of turn order
        stop = JointParallelDataSetIterator(*srcs(2, 4))
        assert sum(1 for _ in stop) == 3  # a0 b0 a1 -> a dry -> stop
        stop2 = JointParallelDataSetIterator(*srcs(4, 2))
        assert sum(1 for _ in stop2) == 4  # a0 b0 a1 b1 -> b dry -> stop
        drain = JointParallelDataSetIterator(*srcs(2, 4),
                                             inequality_handling="pass")
        assert sum(1 for _ in drain) == 6
        rst = JointParallelDataSetIterator(*srcs(2, 4),
                                           inequality_handling="reset")
        # short source replays until the long one finishes: a b a b a b a b
        assert sum(1 for _ in rst) == 8
        # equal-length sources: exactly one pass each, no spurious replay
        eq = JointParallelDataSetIterator(*srcs(2, 2),
                                          inequality_handling="reset")
        assert sum(1 for _ in eq) == 4

    def test_dataset_save_load_and_file_iterator(self, tmp_path):
        from deeplearning4j_tpu.data import FileDataSetIterator

        batches = _blobs(12).batch_by(4)
        for i, b in enumerate(batches):
            # extension-less path: save() must append .npz and return the
            # real on-disk path
            real = b.save(str(tmp_path / f"part{i}"))
            assert real.endswith(".npz") and os.path.exists(real)
        it = FileDataSetIterator(str(tmp_path))
        loaded = list(it)
        assert len(loaded) == 3
        np.testing.assert_array_equal(loaded[0].features, batches[0].features)
        np.testing.assert_array_equal(loaded[0].labels, batches[0].labels)
        # masked sequence round-trip
        ds = DataSet(np.zeros((2, 3, 1), np.float32), np.ones((2, 3, 1), np.float32),
                     np.ones((2, 3), np.float32), np.ones((2, 3), np.float32))
        p = str(tmp_path / "seq.npz")
        ds.save(p)
        back = DataSet.load(p)
        assert back.features_mask is not None and back.labels_mask.shape == (2, 3)

    def test_splitter_views_have_independent_preprocessors(self):
        from deeplearning4j_tpu.data import DataSetIteratorSplitter

        class AddOne:
            def pre_process(self, ds):
                ds.features = ds.features + 1.0
                return ds

        inner = ListDataSetIterator(_blobs(40), 8)  # 5 batches
        sp = DataSetIteratorSplitter(inner, 5, 0.6)
        tr, te = sp.get_train_iterator(), sp.get_test_iterator()
        tr.set_pre_processor(AddOne())  # train only
        raw_first = _blobs(40).features[:8]
        np.testing.assert_allclose(tr.next().features, raw_first + 1.0)
        # test view untouched by the train view's processor
        t = list(te)
        assert len(t) == 2
        np.testing.assert_allclose(t[0].features,
                                   _blobs(40).features[24:32])

    def test_rebatch_mixed_mask_parts_get_all_ones(self):
        from deeplearning4j_tpu.data import IteratorDataSetIterator

        masked = DataSet(np.zeros((2, 4, 3), np.float32),
                         np.zeros((2, 4, 2), np.float32),
                         np.array([[1, 1, 0, 0], [1, 1, 1, 0]], np.float32),
                         np.array([[1, 1, 0, 0], [1, 1, 1, 0]], np.float32))
        unmasked = DataSet(np.ones((2, 4, 3), np.float32),
                           np.ones((2, 4, 2), np.float32))
        it = IteratorDataSetIterator([masked, unmasked], 4)
        out = it.next()
        assert out.features_mask is not None
        np.testing.assert_allclose(out.features_mask[2:], 1.0)
        np.testing.assert_allclose(out.features_mask[:2],
                                   masked.features_mask)

    def test_combined_and_dummy_preprocessor(self):
        from deeplearning4j_tpu.data import CombinedPreProcessor, DummyPreProcessor

        ds = _blobs(16)
        norm = NormalizerStandardize()
        norm.fit(ds)
        it = ListDataSetIterator(ds, 16)
        it.set_pre_processor(CombinedPreProcessor(DummyPreProcessor(), norm))
        out = it.next()
        np.testing.assert_allclose(out.features.mean(), 0.0, atol=1e-6)


class TestMultiDataSetPreProcessor:
    def test_existing_multi_iterator_applies_without_mutating(self):
        from deeplearning4j_tpu.data import (
            ExistingMultiDataSetIterator, MultiDataSet,
        )

        mds = MultiDataSet([np.ones((2, 3), np.float32)],
                           [np.ones((2, 1), np.float32)])

        class Scale:
            def pre_process(self, m):
                m.features = [f * 2.0 for f in m.features]
                return m

        it = ExistingMultiDataSetIterator([mds])
        it.set_pre_processor(Scale())
        out = it.next()
        np.testing.assert_allclose(out.features[0], 2.0)
        it.reset()
        out2 = it.next()
        np.testing.assert_allclose(out2.features[0], 2.0)  # not 4.0
        np.testing.assert_allclose(mds.features[0], 1.0)   # source raw


class TestMultiDataSetIteratorVariants:
    """reference Multi variants of the utility combinators:
    Adapter/Singleton/EarlyTermination/Async(+Shield)/Benchmark/
    Iterator-rebatch/Splitter."""

    def _mds(self, n=8, seed=0):
        from deeplearning4j_tpu.data.dataset import MultiDataSet

        rng = np.random.default_rng(seed)
        return MultiDataSet(
            [rng.random((n, 4)).astype(np.float32)],
            [np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]])

    def test_adapter_singleton_early_benchmark(self):
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.data.iterators import (
            BenchmarkMultiDataSetIterator,
            EarlyTerminationMultiDataSetIterator,
            ExistingMultiDataSetIterator,
            MultiDataSetIteratorAdapter,
            SingletonMultiDataSetIterator,
        )

        ds = DataSet(np.ones((4, 3), np.float32),
                     np.eye(2, dtype=np.float32)[[0, 1, 0, 1]])
        ad = MultiDataSetIteratorAdapter(ListDataSetIterator(ds, 4))
        out = list(ad)
        assert len(out) == 1 and isinstance(out[0], MultiDataSet)
        assert out[0].features[0].shape == (4, 3)

        s = SingletonMultiDataSetIterator(self._mds())
        assert len(list(s)) == 1 and len(list(s)) == 1  # resets via iter

        inner = ExistingMultiDataSetIterator([self._mds(seed=i)
                                              for i in range(5)])
        et = EarlyTerminationMultiDataSetIterator(inner, 3)
        assert len(list(et)) == 3

        b = BenchmarkMultiDataSetIterator(self._mds(), 7)
        assert len(list(b)) == 7

    def test_async_multi_and_shield(self):
        from deeplearning4j_tpu.data.iterators import (
            AsyncMultiDataSetIterator,
            AsyncShieldMultiDataSetIterator,
            ExistingMultiDataSetIterator,
        )

        src = [self._mds(seed=i) for i in range(6)]
        a = AsyncMultiDataSetIterator(
            ExistingMultiDataSetIterator(src), queue_size=2)
        got = list(a)
        assert len(got) == 6
        np.testing.assert_array_equal(got[2].features[0], src[2].features[0])
        got2 = list(a)  # reset + second epoch
        assert len(got2) == 6
        sh = AsyncShieldMultiDataSetIterator(
            ExistingMultiDataSetIterator(src))
        assert sh.async_supported() is False
        assert len(list(sh)) == 6

    def test_iterator_rebatch_and_splitter(self):
        from deeplearning4j_tpu.data.iterators import (
            ExistingMultiDataSetIterator,
            IteratorMultiDataSetIterator,
            MultiDataSetIteratorSplitter,
        )

        pieces = [self._mds(n=3, seed=i) for i in range(5)]  # 15 examples
        it = IteratorMultiDataSetIterator(pieces, batch_size=4)
        sizes = [m.num_examples() for m in it]
        assert sum(sizes) == 15
        assert all(s == 4 for s in sizes[:-1]), sizes
        # identical content in order
        cat = np.concatenate([m.features[0] for m in it], 0)
        ref = np.concatenate([p.features[0] for p in pieces], 0)
        np.testing.assert_array_equal(cat, ref)

        sp = MultiDataSetIteratorSplitter(
            ExistingMultiDataSetIterator([self._mds(seed=i)
                                          for i in range(10)]),
            total_batches=10, ratio=0.7)
        assert len(list(sp.get_train_iterator())) == 7
        assert len(list(sp.get_test_iterator())) == 3

    def test_cg_fit_through_adapter_and_async(self):
        """ComputationGraph trains from a DataSet source wrapped
        Adapter -> AsyncMulti (the reference CG fit path shape)."""
        from deeplearning4j_tpu.data.iterators import (
            AsyncMultiDataSetIterator,
            MultiDataSetIteratorAdapter,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.updaters import Adam

        rng = np.random.default_rng(5)
        x = rng.standard_normal((64, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
        it = AsyncMultiDataSetIterator(MultiDataSetIteratorAdapter(
            ListDataSetIterator(DataSet(x, y), 16)))
        gconf = (
            NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
            .weight_init("xavier").graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("o", OutputLayer(n_out=2, activation="softmax",
                                        loss="mcxent"), "d")
            .set_outputs("o")
            .set_input_types(InputType.feed_forward(4)).build()
        )
        g = ComputationGraph(gconf).init()
        for _ in range(5):
            g.fit(it)
        assert float(g.score_) < 0.6


class TestMultiVariantReviewRegressions:
    def _mds(self, n=4, T=None, mask=False, seed=0):
        from deeplearning4j_tpu.data.dataset import MultiDataSet

        rng = np.random.default_rng(seed)
        if T:
            f = rng.random((n, T, 3)).astype(np.float32)
            fm = np.ones((n, T), np.float32) if mask else None
            return MultiDataSet([f], [f.copy()], [fm], [fm])
        return MultiDataSet(
            [rng.random((n, 3)).astype(np.float32)],
            [np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]])

    def test_splitter_views_apply_multi_preprocessor(self):
        from deeplearning4j_tpu.data.iterators import (
            ExistingMultiDataSetIterator,
            MultiDataSetIteratorSplitter,
        )

        class Doubler:
            def pre_process(self, mds):
                mds.features = [f * 2 for f in mds.features]
                return mds

        src = [self._mds(seed=i) for i in range(4)]
        sp = MultiDataSetIteratorSplitter(
            ExistingMultiDataSetIterator(src), total_batches=4, ratio=0.5)
        tr = sp.get_train_iterator()
        tr.set_pre_processor(Doubler())
        got = list(tr)
        assert len(got) == 2
        np.testing.assert_allclose(got[0].features[0],
                                   src[0].features[0] * 2)
        # source batches stay raw (shallow-copy contract)
        assert src[0].features[0].max() <= 1.0

    def test_rebatch_mixed_mask_synthesizes_ones(self):
        from deeplearning4j_tpu.data.iterators import (
            IteratorMultiDataSetIterator,
        )

        pieces = [self._mds(n=2, T=5, mask=True, seed=0),
                  self._mds(n=3, T=5, mask=False, seed=1)]
        it = IteratorMultiDataSetIterator(pieces, batch_size=5)
        m = it.next()
        assert m.features_masks[0].shape == (5, 5)
        np.testing.assert_array_equal(m.features_masks[0][2:],
                                      np.ones((3, 5), np.float32))
