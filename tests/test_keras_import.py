"""Keras HDF5 import golden-output tests.

Mirrors the reference's model-import test pattern: fixture HDF5s generated
by in-tree scripts (``tests/fixtures/gen_keras_fixtures.py``, the
reference's ``modelimport/.../weights/scripts/`` pattern), asserting the
imported model's forward pass matches Keras' recorded outputs
(``KerasModelEndToEndTest.java`` style, tolerance 1e-4).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import KerasModelImport
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "keras")

SEQUENTIAL = ["mlp", "cnn", "lstm", "mobilenet_mini", "text_bilstm",
              # legacy/contrib layer mappers (VERDICT r3 item 5):
              # KerasLRN, KerasSpaceToDepth, KerasAtrousConvolution1D/2D
              "lrn", "space_to_depth", "atrous2d", "atrous1d"]
FUNCTIONAL = ["functional", "inception_mini"]


def _golden(name):
    data = np.load(os.path.join(FIXTURES, f"{name}_golden.npz"))
    return data["x"], data["y"]


@pytest.mark.parametrize("name", SEQUENTIAL)
def test_sequential_import_matches_keras(name):
    path = os.path.join(FIXTURES, f"{name}.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    assert isinstance(net, MultiLayerNetwork)
    x, y = _golden(name)
    out = net.output(x)
    np.testing.assert_allclose(out, y, atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("name", FUNCTIONAL)
def test_functional_import_matches_keras(name):
    path = os.path.join(FIXTURES, f"{name}.h5")
    net = KerasModelImport.import_keras_model_and_weights(path)
    assert isinstance(net, ComputationGraph)
    x, y = _golden(name)
    out = net.output_single(x)
    np.testing.assert_allclose(out, y, atol=1e-4, rtol=1e-3)


def test_type_dispatch_sequential_via_generic_entry():
    net = KerasModelImport.import_keras_model_and_weights(
        os.path.join(FIXTURES, "mlp.h5")
    )
    assert isinstance(net, MultiLayerNetwork)


def test_imported_model_is_trainable():
    """Imported nets are ordinary networks: fit must run and reduce loss."""
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        os.path.join(FIXTURES, "mlp.h5")
    )
    from deeplearning4j_tpu.data.dataset import DataSet

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    net.fit(DataSet(x, y), epochs=3, batch_size=16)
    assert np.isfinite(net.score())


def test_imported_model_serializes():
    """Imported model round-trips through the native checkpoint format."""
    from deeplearning4j_tpu.train.model_serializer import ModelSerializer

    net = KerasModelImport.import_keras_sequential_model_and_weights(
        os.path.join(FIXTURES, "cnn.h5")
    )
    x, _ = _golden("cnn")
    path = "/tmp/keras_import_roundtrip.zip"
    ModelSerializer.write_model(net, path, save_updater=False)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    np.testing.assert_allclose(net.output(x), net2.output(x), atol=1e-6)


def test_missing_mapper_error_is_informative():
    from deeplearning4j_tpu.modelimport.keras.mappers import (
        UnsupportedKerasLayer,
        map_keras_layer,
    )

    with pytest.raises(UnsupportedKerasLayer, match="No mapper"):
        map_keras_layer("LocallyConnected2D", {})


def test_keras1_atrous_config_keys():
    """Keras-1 config vocabulary (nb_filter/nb_row/nb_col/subsample/
    atrous_rate/border_mode) maps onto the same layers the Keras-2 keys
    do (reference KerasAtrousConvolution1D/2D.java parse keras-1 files)."""
    from deeplearning4j_tpu.modelimport.keras.mappers import map_keras_layer

    m2 = map_keras_layer("AtrousConvolution2D", {
        "nb_filter": 6, "nb_row": 3, "nb_col": 5, "subsample": [2, 1],
        "atrous_rate": [2, 2], "border_mode": "valid",
    })
    l2 = m2.layer
    assert l2.n_out == 6 and l2.kernel_size == [3, 5]
    assert l2.stride == [2, 1] and l2.dilation == [2, 2]
    assert l2.convolution_mode == "truncate"

    m1 = map_keras_layer("AtrousConvolution1D", {
        "nb_filter": 4, "filter_length": 3, "subsample_length": 1,
        "atrous_rate": 2, "border_mode": "same",
    })
    l1 = m1.layer
    assert l1.n_out == 4 and l1.kernel_size == [3]
    assert l1.dilation == [2] and l1.convolution_mode == "same"


def test_lrn_mapper_defaults():
    """KerasLRN.java defaults: k=2, n=5, alpha=1e-4, beta=0.75."""
    from deeplearning4j_tpu.modelimport.keras.mappers import map_keras_layer

    layer = map_keras_layer("LRN2D", {}).layer
    assert (layer.k, layer.n, layer.alpha, layer.beta) == (2.0, 5.0, 1e-4, 0.75)


# --------------------------------------------------------------------------
# Keras 3 .keras zip format (round-3: format-support expansion)
# --------------------------------------------------------------------------
K3_SEQUENTIAL = ["k3_mlp", "k3_cnn", "k3_lstm"]


@pytest.mark.parametrize("name", K3_SEQUENTIAL)
def test_keras3_zip_import_matches_golden(name):
    path = os.path.join(FIXTURES, f"{name}.keras")
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    assert isinstance(net, MultiLayerNetwork)
    x, y = _golden(name)
    out = net.output(x)
    np.testing.assert_allclose(out, y, atol=1e-4, rtol=1e-3)


def test_keras3_zip_imported_model_trains(): 
    from deeplearning4j_tpu.data.dataset import DataSet

    net = KerasModelImport.import_keras_sequential_model_and_weights(
        os.path.join(FIXTURES, "k3_mlp.keras")
    )
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    net.fit(DataSet(x, y), epochs=3, batch_size=16)
    assert np.isfinite(net.score())


def test_uncompiled_model_without_inferable_loss_errors_loudly():
    """No training_config + linear output: must raise, not silently
    default to mse (round-2 verdict weak #7)."""
    path = os.path.join(FIXTURES, "k3_uncompiled.keras")
    with pytest.raises(ValueError, match="default_loss"):
        KerasModelImport.import_keras_sequential_model_and_weights(path)
    # explicit default_loss resolves it
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        path, default_loss="mse"
    )
    x, y = _golden("k3_uncompiled")
    np.testing.assert_allclose(net.output(x), y, atol=1e-4, rtol=1e-3)


# --------------------------------------------------------------------------
# Full-size real-architecture import (BASELINE config #4: MobileNet /
# InceptionV3). Pretrained weights are not obtainable offline (zero
# egress), so keras.applications architectures are instantiated with
# random weights at test time — the layer mapping, weight layouts and
# graph assembly are identical to the pretrained case.
# --------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("arch,shape,tol", [
    ("MobileNetV2", (96, 96, 3), 1e-4),
    ("InceptionV3", (96, 96, 3), 1e-4),
])
def test_full_size_application_import(arch, shape, tol, tmp_path, monkeypatch):
    keras = pytest.importorskip("keras")
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "-1")
    keras.utils.set_random_seed(5)
    kwargs = dict(weights=None, input_shape=shape, classes=50)
    model = getattr(keras.applications, arch)(**kwargs)
    model.compile(loss="categorical_crossentropy")
    path = str(tmp_path / f"{arch}.h5")
    model.save(path)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2,) + shape).astype(np.float32)
    y = model.predict(x, verbose=0)

    net = KerasModelImport.import_keras_model_and_weights(path)
    out = net.output_single(x)
    np.testing.assert_allclose(out, y, atol=tol, rtol=1e-3)


def test_channels_first_model_imports_with_layout_translation():
    """Theano/NCHW-era models import into the NHWC runtime: the Flatten →
    Dense kernel rows are permuted from (c,h,w) to (h,w,c) ordering and
    the caller feeds NHWC inputs (round-2 verdict weak #7: previously
    rejected outright)."""
    path = os.path.join(FIXTURES, "channels_first.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    assert getattr(net, "channels_first_source", False)
    d = np.load(os.path.join(FIXTURES, "channels_first_golden.npz"))
    out = net.output(d["x_nhwc"])
    np.testing.assert_allclose(out, d["y"], atol=1e-4, rtol=1e-3)


class TestKeras1FlattenPermutation:
    def test_perm_math(self):
        """flatten(x_chw)[perm] == flatten(x_hwc) — the defining identity
        of the Keras-1 NCHW flatten translation."""
        from deeplearning4j_tpu.modelimport.keras.importer import (
            _chw_to_hwc_perm,
        )

        rng = np.random.default_rng(0)
        h, w, c = 3, 4, 5
        x_hwc = rng.standard_normal((h, w, c))
        x_chw = np.transpose(x_hwc, (2, 0, 1))
        perm = _chw_to_hwc_perm(h, w, c)
        np.testing.assert_array_equal(x_chw.reshape(-1)[perm],
                                      x_hwc.reshape(-1))

    def test_keras1_version_triggers_permutation(self, tmp_path):
        """A channels_first file whose keras_version reads 1.x gets its
        first post-Flatten Dense kernel row-permuted (Keras 2/3 files do
        not — covered by the golden-parity test)."""
        import shutil

        import h5py

        src = os.path.join(FIXTURES, "channels_first.h5")
        k1 = str(tmp_path / "cf_keras1.h5")
        shutil.copy(src, k1)
        with h5py.File(k1, "r+") as f:
            f.attrs["keras_version"] = "1.2.2"
            if "model_weights" in f:
                f["model_weights"].attrs["keras_version"] = "1.2.2"

        net3 = KerasModelImport.import_keras_sequential_model_and_weights(src)
        net1 = KerasModelImport.import_keras_sequential_model_and_weights(k1)
        from deeplearning4j_tpu.modelimport.keras.importer import (
            _chw_to_hwc_perm,
        )

        # dense fed by flatten is layer index 2 (conv, pool, dense, dense)
        W3 = np.asarray(net3.params_[2]["W"])
        W1 = np.asarray(net1.params_[2]["W"])
        perm = _chw_to_hwc_perm(4, 4, 4)  # pool output h,w,c
        np.testing.assert_allclose(W1, W3[perm, :], atol=0)
        assert not np.allclose(W1, W3)


def test_architecture_json_plus_weights_pair():
    """Reference overload importKerasModelAndWeights(modelJson,
    weightsHdf5): architecture JSON + weights-only .weights.h5 (positional
    layout, same as the .keras zip) import with golden parity."""
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        os.path.join(FIXTURES, "jw_arch.json"),
        weights_path=os.path.join(FIXTURES, "jw.weights.h5"),
        default_loss="mcxent",
    )
    d = np.load(os.path.join(FIXTURES, "jw_golden.npz"))
    np.testing.assert_allclose(net.output(d["x"]), d["y"], atol=1e-4,
                               rtol=1e-3)
