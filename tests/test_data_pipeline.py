"""Sharded input pipeline tests (data/shards.py + data/loader.py +
data/augment.py).

The format round-trips bit-exact and rejects damage typed (CRC flip,
truncation, manifest drift all surface as TornShardError — never a
struct.error or a silently-wrong batch); the multi-worker loader's
stream is deterministic in (seed, epoch, step) and INDEPENDENT of the
worker count; resume from a mid-epoch data_state replays the exact
remaining stream with the rolling fingerprint chain continuing to the
oracle's final value; per-host shard assignment partitions the shard
set disjointly; a torn shard is skipped typed with a ``shard_skip``
forensic while the epoch completes; the data position rides checkpoint
meta through both serializers; and the on-device augmentation stage is
iteration-keyed, bundle-consistent and traces exactly once.
"""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ExistingDataSetIterator
from deeplearning4j_tpu.data.loader import ShardedLoader
from deeplearning4j_tpu.data.shards import (
    TornShardError,
    assign_host_shards,
    load_manifest,
    pack_iterator,
    read_shard,
    shard_name,
    verify_dir,
    verify_shard,
    write_shard,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import flight
from deeplearning4j_tpu.updaters import Adam

N_IN, N_HID, N_OUT = 4, 6, 3


def _net(seed=3):
    conf = (
        NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
        .list()
        .layer(DenseLayer(n_out=N_HID, activation="tanh"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _batches(n=4, per=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((per, N_IN)).astype(np.float32)
        y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, per)]
        out.append(DataSet(x, y))
    return out


def _pack(tmp_path, n=12, per=8, seed=0, batches_per_shard=3):
    d = str(tmp_path / "shards")
    pack_iterator(ExistingDataSetIterator(_batches(n, per, seed)), d,
                  batches_per_shard=batches_per_shard)
    return d


def _drain(loader):
    """Consume one epoch; returns (list-of-(features, labels), state)."""
    out = []
    while loader.has_next():
        ds = loader.next()
        out.append((np.asarray(ds.features).copy(),
                    np.asarray(ds.labels).copy()))
    return out, loader.data_state()


class TestShardFormat:
    def test_roundtrip_bit_exact(self, tmp_path):
        batches = _batches(5, per=6, seed=2)
        p = str(tmp_path / shard_name(0, 1))
        write_shard(p, batches)
        back = read_shard(p)
        assert len(back) == 5
        for a, b in zip(batches, back):
            np.testing.assert_array_equal(np.asarray(a.features),
                                          np.asarray(b.features))
            np.testing.assert_array_equal(np.asarray(a.labels),
                                          np.asarray(b.labels))

    def test_ragged_tail_batch(self, tmp_path):
        batches = _batches(2, per=8) + _batches(1, per=3, seed=9)
        p = str(tmp_path / shard_name(0, 1))
        write_shard(p, batches)
        back = read_shard(p)
        assert [np.asarray(b.features).shape[0] for b in back] == [8, 8, 3]

    def test_crc_flip_rejected_typed(self, tmp_path):
        p = str(tmp_path / shard_name(0, 1))
        write_shard(p, _batches(4))
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0xFF  # one payload bit-flip
        open(p, "wb").write(bytes(raw))
        with pytest.raises(TornShardError) as ei:
            read_shard(p)
        assert "CRC" in str(ei.value)

    def test_truncation_rejected_typed(self, tmp_path):
        p = str(tmp_path / shard_name(0, 1))
        write_shard(p, _batches(4))
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[: len(raw) * 2 // 3])
        with pytest.raises(TornShardError):
            read_shard(p)
        assert not verify_shard(p)["ok"]

    def test_verify_never_raises(self, tmp_path):
        p = str(tmp_path / shard_name(0, 1))
        write_shard(p, _batches(3))
        assert verify_shard(p) == {"path": p, "ok": True, "records": 3,
                                   "error": None}
        open(p, "wb").write(b"not a shard at all")
        r = verify_shard(p)
        assert not r["ok"] and r["error"]

    def test_pack_manifest_and_verify_dir(self, tmp_path):
        d = _pack(tmp_path, n=10, batches_per_shard=4)
        m = load_manifest(d)
        assert m["num_shards"] == 3  # 4 + 4 + 2
        assert m["total_batches"] == 10
        assert [s["records"] for s in m["shards"]] == [4, 4, 2]
        assert m["schema"]["features"]["shape"] == [N_IN]
        assert verify_dir(d)["ok"]

    def test_verify_dir_flags_missing_and_count_drift(self, tmp_path):
        d = _pack(tmp_path, n=6, batches_per_shard=3)
        m = load_manifest(d)
        os.remove(os.path.join(d, m["shards"][1]["name"]))
        r = verify_dir(d)
        assert not r["ok"] and r["bad"] == 1
        assert "missing" in r["shards"][1]["error"]

    def test_missing_manifest_typed(self, tmp_path):
        with pytest.raises(TornShardError):
            load_manifest(str(tmp_path))

    def test_no_tmp_litter(self, tmp_path):
        d = _pack(tmp_path)
        litter = [f for f in os.listdir(d) if ".tmp-" in f]
        assert litter == []


class TestHostAssignment:
    def test_partition_disjoint_and_complete(self):
        parts = assign_host_shards(10, 4)
        flat = sorted(i for p in parts for i in p)
        assert flat == list(range(10))
        assert len(parts) == 4
        # round-robin spread: no host more than ceil(10/4)=3
        assert max(len(p) for p in parts) <= 3

    def test_single_host_owns_all(self):
        assert assign_host_shards(5, 1, 0) == [0, 1, 2, 3, 4]

    def test_bad_host_index_typed(self):
        with pytest.raises(ValueError):
            assign_host_shards(4, 2, 2)

    def test_two_host_loaders_disjoint_union_is_all(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=2)  # 6 shards
        streams = []
        for h in range(2):
            ld = ShardedLoader(d, num_workers=2, seed=5, host_index=h,
                               host_count=2)
            got, _ = _drain(ld)
            ld.shutdown()
            streams.append(got)
        keys = [{arr[0].tobytes() for arr in s} for s in streams]
        assert not (keys[0] & keys[1])
        all_feats = {np.asarray(b.features).tobytes()
                     for b in _batches(12)}
        assert keys[0] | keys[1] == all_feats


class TestLoaderDeterminism:
    def test_worker_count_invariance(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)
        ref = None
        for workers in (1, 3):
            ld = ShardedLoader(d, num_workers=workers, seed=7)
            got, st = _drain(ld)
            ld.shutdown()
            sig = [f.tobytes() + l.tobytes() for f, l in got]
            if ref is None:
                ref, ref_fp = sig, st["fingerprint"]
            else:
                assert sig == ref
                assert st["fingerprint"] == ref_fp

    def test_epochs_reshuffle_deterministically(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)
        ld = ShardedLoader(d, num_workers=1, seed=1)
        assert ld.epoch_plan(0) != ld.epoch_plan(1)  # reshuffled
        assert ld.epoch_plan(0) == ld.epoch_plan(0)  # but pinned
        e0, _ = _drain(ld)
        ld.reset()
        e1, _ = _drain(ld)
        ld.shutdown()
        # same bytes, different order across epochs
        assert ([x[0].tobytes() for x in e0]
                != [x[0].tobytes() for x in e1])
        assert (sorted(x[0].tobytes() for x in e0)
                == sorted(x[0].tobytes() for x in e1))
        # a fresh loader with the same seed replays epoch 0 exactly
        ld2 = ShardedLoader(d, num_workers=2, seed=1)
        again, _ = _drain(ld2)
        ld2.shutdown()
        assert ([x[0].tobytes() for x in again]
                == [x[0].tobytes() for x in e0])

    def test_seed_changes_stream(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)
        orders = []
        for seed in (0, 1):
            ld = ShardedLoader(d, num_workers=1, seed=seed)
            got, _ = _drain(ld)
            ld.shutdown()
            orders.append([x[0].tobytes() for x in got])
        assert orders[0] != orders[1]

    def test_resume_mid_epoch_bit_identical(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)
        oracle = ShardedLoader(d, num_workers=2, seed=9)
        full, ostate = _drain(oracle)
        oracle.shutdown()

        # consume 5 batches, snapshot, abandon (the SIGKILL analogue:
        # the state dict is all that survives)
        first = ShardedLoader(d, num_workers=2, seed=9)
        for _ in range(5):
            first.next()
        snap = first.data_state()
        first.shutdown()
        assert snap["batches"] == 5

        resumed = ShardedLoader(d, num_workers=1, seed=9)
        resumed.restore_state(snap)
        tail, rstate = _drain(resumed)
        resumed.shutdown()
        assert len(tail) == len(full) - 5
        for (f, l), (rf, rl) in zip(full[5:], tail):
            assert f.tobytes() == rf.tobytes()
            assert l.tobytes() == rl.tobytes()
        # the rolling fingerprint chain continued to the oracle's value
        assert rstate["fingerprint"] == ostate["fingerprint"]
        assert rstate["batches"] == ostate["batches"]

    def test_restore_rejects_mismatched_world(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)
        ld = ShardedLoader(d, num_workers=1, seed=4)
        st = ld.data_state()
        ld.shutdown()
        other = ShardedLoader(d, num_workers=1, seed=5)
        with pytest.raises(ValueError):
            other.restore_state(st)  # seed mismatch = different stream
        other.shutdown()

    def test_torn_shard_skipped_typed_with_forensic(self, tmp_path):
        d = _pack(tmp_path, n=12, batches_per_shard=3)  # 4 shards
        ld = ShardedLoader(d, num_workers=2, seed=11)
        victim = ld.epoch_plan(0)[1]
        path = os.path.join(d, ld._names[victim])
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 2])
        seq0 = flight.default_flight_recorder().recorded_total
        got, st = _drain(ld)
        ld.shutdown()
        assert len(got) == 9  # 12 minus the torn shard's 3
        skips = [e for e in flight.default_flight_recorder().events()
                 if e["kind"] == "shard_skip"]
        assert skips and skips[-1]["seq"] > seq0
        assert st["batches"] == 9


class TestProvenance:
    def test_fit_records_data_state(self, tmp_path):
        d = _pack(tmp_path, n=8, batches_per_shard=2)
        ld = ShardedLoader(d, num_workers=2, seed=3)
        model = _net()
        model.fit(ld, epochs=1)
        ld.shutdown()
        st = model._data_state
        assert st is not None
        assert st["format"] == "sharded_loader/v1"
        assert st["batches"] == 8 and model.iteration == 8

    @pytest.mark.parametrize("serializer", ["zip", "orbax"])
    def test_data_state_rides_checkpoint_meta(self, tmp_path, serializer):
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        d = _pack(tmp_path, n=6, batches_per_shard=2)
        ld = ShardedLoader(d, num_workers=1, seed=2)
        model = _net()
        ckdir = str(tmp_path / f"ck_{serializer}")
        lst = CheckpointListener(ckdir, save_every_n_epochs=1,
                                 keep_mode="last", serializer=serializer)
        model.add_listeners(lst)
        model.fit(ld, epochs=1)
        ld.shutdown()

        if serializer == "orbax":
            from deeplearning4j_tpu.train.orbax_serializer import (
                OrbaxModelSerializer,
            )

            restored = OrbaxModelSerializer.restore(lst.checkpoints[-1])
        else:
            from deeplearning4j_tpu.train.faults import load_latest_valid

            restored, _path = load_latest_valid(ckdir)
        st = restored._data_state
        assert st is not None and st["batches"] == 6
        assert st["fingerprint"] == model._data_state["fingerprint"]

        # and a fresh loader restored from it continues the stream
        ld2 = ShardedLoader(d, num_workers=2, seed=2)
        ld2.restore_state(st)
        assert ld2.data_state()["fingerprint"] == st["fingerprint"]
        ld2.shutdown()

    def test_fit_resume_stream_matches_oracle(self, tmp_path):
        d = _pack(tmp_path, n=9, batches_per_shard=3)
        oracle_ld = ShardedLoader(d, num_workers=1, seed=6)
        oracle = _net(seed=5)
        oracle.fit(oracle_ld, epochs=2)
        ofp = oracle_ld.data_state()["fingerprint"]
        oracle_ld.shutdown()

        ld_a = ShardedLoader(d, num_workers=2, seed=6)
        m = _net(seed=5)
        m.fit(ld_a, epochs=1)
        state = m._data_state
        ld_a.shutdown()

        ld_b = ShardedLoader(d, num_workers=3, seed=6)
        ld_b.restore_state(state)
        m.fit(ld_b, epochs=1)
        assert ld_b.data_state()["fingerprint"] == ofp
        ld_b.shutdown()
        np.testing.assert_array_equal(
            np.asarray(m.params_flat()), np.asarray(oracle.params_flat()))


class TestAugmentation:
    def test_deterministic_and_iteration_keyed(self):
        from deeplearning4j_tpu.data.augment import parse_augment_spec

        st = parse_augment_spec("normalize:0.5:0.25,crop:2,noise:0.05",
                                seed=7)
        x = np.random.default_rng(0).random((4, 10, 10, 3),
                                            dtype=np.float32)
        a0 = np.asarray(st.apply(x, 0))
        a1 = np.asarray(st.apply(x, 1))
        assert a0.shape == x.shape
        assert not np.array_equal(a0, a1)
        np.testing.assert_array_equal(a0, np.asarray(st.apply(x, 0)))

    def test_bundle_matches_per_step_fold_in(self):
        from deeplearning4j_tpu.data.augment import AugmentStage

        st = AugmentStage(noise=0.1, seed=3)
        x = np.random.default_rng(1).random((4, N_IN), dtype=np.float32)
        bundle = np.stack([x, x])
        ob = np.asarray(st.apply_bundle(bundle, 10))
        np.testing.assert_array_equal(ob[0], np.asarray(st.apply(x, 10)))
        np.testing.assert_array_equal(ob[1], np.asarray(st.apply(x, 11)))

    def test_zero_steady_state_retraces(self):
        from deeplearning4j_tpu.data.augment import AugmentStage
        from deeplearning4j_tpu.obs.trace import retrace_counts

        st = AugmentStage(normalize=(0.0, 1.0), noise=0.01, seed=1)
        x = np.random.default_rng(2).random((8, N_IN), dtype=np.float32)
        before = retrace_counts().get("augment_batch", 0)
        for it in range(6):
            st.apply(x, it)
        # the retrace counter is process-global (other stages in this
        # run traced too): assert THIS stage added exactly one trace
        assert retrace_counts().get("augment_batch", 0) - before == 1

    def test_bad_spec_typed(self):
        from deeplearning4j_tpu.data.augment import parse_augment_spec

        with pytest.raises(ValueError):
            parse_augment_spec("flip:1")
        with pytest.raises(ValueError):
            parse_augment_spec("normalize:a:b")

    def test_fit_with_augment_converges_and_traces_once(self, tmp_path):
        from deeplearning4j_tpu.data.augment import AugmentStage
        from deeplearning4j_tpu.obs.trace import retrace_counts

        d = _pack(tmp_path, n=6, batches_per_shard=2)
        ld = ShardedLoader(d, num_workers=1, seed=1)
        model = _net()
        model.set_augmentation(AugmentStage(normalize=(0.0, 1.0),
                                            noise=0.02, seed=4))
        before = retrace_counts().get("augment_batch", 0)
        model.fit(ld, epochs=2)
        ld.shutdown()
        assert model.iteration == 12
        assert np.isfinite(float(model.score_))
        # 12 augmented steps across 2 epochs, ONE trace of this stage
        assert retrace_counts().get("augment_batch", 0) - before == 1


class TestMixup:
    def _xy(self, b=8, seed=1, classes=4):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((b, N_IN)).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, b)]
        return x, y

    def test_mixup_zero_is_fingerprint_stable(self):
        """mixup=0 must leave the key stream byte-identical to a stage
        built before the knob existed — same seed, same crops/noise."""
        from deeplearning4j_tpu.data.augment import AugmentStage

        x, _ = self._xy()
        a = AugmentStage(noise=0.1, seed=3)
        b = AugmentStage(noise=0.1, mixup=0.0, seed=3)
        np.testing.assert_array_equal(np.asarray(a.apply(x, 5)),
                                      np.asarray(b.apply(x, 5)))

    def test_spec_roundtrip_and_mixes_labels(self):
        from deeplearning4j_tpu.data.augment import parse_augment_spec

        st = parse_augment_spec("normalize:0.0:1.0,mixup:0.4", seed=2)
        assert st.mixup == 0.4
        assert st.mixes_labels
        assert "mixup:0.4" in st.spec()
        assert not parse_augment_spec("noise:0.1").mixes_labels

    def test_negative_alpha_typed(self):
        from deeplearning4j_tpu.data.augment import AugmentStage

        with pytest.raises(ValueError, match="mixup"):
            AugmentStage(mixup=-0.1)

    def test_pair_label_consistent_deterministic_one_trace(self):
        from deeplearning4j_tpu.data.augment import AugmentStage
        from deeplearning4j_tpu.obs.trace import retrace_counts

        st = AugmentStage(mixup=0.4, seed=2)
        x, y = self._xy()
        before = retrace_counts().get("augment_pair", 0)
        x1, y1 = map(np.asarray, st.apply_pair(x, y, 0))
        x2, _y2 = map(np.asarray, st.apply_pair(x, y, 1))
        assert retrace_counts().get("augment_pair", 0) - before == 1
        assert not np.array_equal(x1, x2)  # iteration changes the mix
        # mixed one-hot labels stay a distribution (same lam/perm as x)
        assert np.allclose(y1.sum(1), 1.0, atol=1e-5)
        x1b, y1b = map(np.asarray, st.apply_pair(x, y, 0))
        np.testing.assert_array_equal(x1, x1b)
        np.testing.assert_array_equal(y1, y1b)

    def test_pair_bundle_matches_per_step_fold_in(self):
        from deeplearning4j_tpu.data.augment import AugmentStage

        st = AugmentStage(mixup=0.3, seed=5)
        x, y = self._xy()
        xb, yb = np.stack([x, x]), np.stack([y, y])
        ox, oy = map(np.asarray, st.apply_pair_bundle(xb, yb, 10))
        ex0, ey0 = map(np.asarray, st.apply_pair(x, y, 10))
        ex1, ey1 = map(np.asarray, st.apply_pair(x, y, 11))
        # same lam/perm per inner step; allclose not bit-equal — the
        # vmapped program fuses the mix multiply-adds differently
        np.testing.assert_allclose(ox[0], ex0, atol=1e-6)
        np.testing.assert_allclose(oy[0], ey0, atol=1e-6)
        np.testing.assert_allclose(ox[1], ex1, atol=1e-6)
        np.testing.assert_allclose(oy[1], ey1, atol=1e-6)

    def test_fit_with_mixup_routes_pair_and_traces_once(self):
        from deeplearning4j_tpu.data.augment import AugmentStage
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.obs.trace import retrace_counts

        x, y = self._xy(classes=N_OUT)
        model = _net()
        model.set_augmentation(AugmentStage(mixup=0.3, seed=0))
        before = retrace_counts().get("augment_pair", 0)
        for _ in range(6):
            model.fit(DataSet(x, y))
        assert retrace_counts().get("augment_pair", 0) - before == 1
        assert np.isfinite(float(model.score_))


class TestObservability:
    def test_mixed_family_snapshot(self):
        """A metric family with BOTH the legacy unlabeled child (async
        prefetch) and pool-labeled children (shard loaders) must stay
        snapshot-able — the regression here broke every later
        snapshot() in the process once both data paths had run."""
        from deeplearning4j_tpu.obs.metrics import (
            MetricsRegistry,
            data_pipeline_metrics,
        )

        reg = MetricsRegistry()
        _, _, legacy = data_pipeline_metrics(reg)
        legacy.inc(0.5)
        _, _, pooled = data_pipeline_metrics(reg, pool="shard_loader")
        pooled.inc(1.25)
        fam = reg.snapshot()["data_consumer_wait_seconds_total"]
        assert fam == {"": 0.5, "pool=shard_loader": 1.25}
        assert "pool=\"shard_loader\"" in reg.prometheus_text().replace(
            "'", "\"")

    def test_alert_rules_declared(self):
        from deeplearning4j_tpu.obs.slo import default_rules

        names = {r.name for r in default_rules()}
        assert {"data_loader_stalled", "shard_skips",
                "data_queue_starved"} <= names

    def test_starved_pools_names_the_loader_pool(self, tmp_path):
        from deeplearning4j_tpu.obs.metrics import (
            MetricsRegistry,
            starved_pools,
        )

        reg = MetricsRegistry()
        d = _pack(tmp_path, n=6, batches_per_shard=2)
        ld = ShardedLoader(d, num_workers=1, seed=1, pool="pool_x",
                           registry=reg)
        _drain(ld)
        ld.shutdown()
        # consumer-wait on a cold loader is near-certain but not
        # guaranteed; assert the label plumbing, not the timing
        pools = starved_pools(reg)
        for name in pools:
            assert name in ("pool_x", "async_prefetch")

    def test_loader_worker_exit_forensics(self, tmp_path):
        d = _pack(tmp_path, n=6, batches_per_shard=2)
        ld = ShardedLoader(d, num_workers=2, seed=1)
        _drain(ld)
        ld.shutdown()
        exits = [e for e in flight.default_flight_recorder().events()
                 if e["kind"] == "loader_worker_exit"]
        assert exits
        assert exits[-1]["reason"] in ("plan_drained", "stopped")


class TestCli:
    def test_data_pack_verify_roundtrip(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import data_main

        out = str(tmp_path / "shards")
        rc = data_main(["pack", "--dataset", "iris", "--batch-size", "8",
                        "--out", out, "--shard-size", "4"])
        assert rc == 0
        assert data_main(["verify", out]) == 0
        capsys.readouterr()

        # corrupt one shard: verify must fail non-zero with a report
        m = load_manifest(out)
        victim = os.path.join(out, m["shards"][0]["name"])
        raw = bytearray(open(victim, "rb").read())
        raw[-5] ^= 0xFF
        open(victim, "wb").write(bytes(raw))
        assert data_main(["verify", out, "--json"]) == 1
        rep = json.loads(capsys.readouterr().out)
        assert not rep["ok"] and rep["bad"] == 1
