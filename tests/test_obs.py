"""Observability layer (obs/): metrics registry + Prometheus exposition,
in-graph telemetry (bit-parity, once-per-bundle fetch discipline),
retrace monitor (the zero-steady-state-recompiles CI guard), exporter
HTTP endpoint, serving /healthz + content negotiation, and the listener
satellites (PerformanceListener accounting, ProfilerListener fit-exit
close, data-pipeline wait gauges).
"""

import http.client
import json
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    ExistingDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import telemetry as obs_telemetry
from deeplearning4j_tpu.obs import trace as obs_trace
from deeplearning4j_tpu.obs.exporter import MetricsServer, wants_prometheus
from deeplearning4j_tpu.obs.metrics import (
    MetricsListener,
    MetricsRegistry,
    data_wait_seconds,
)
from deeplearning4j_tpu.obs.telemetry import TelemetryConf
from deeplearning4j_tpu.train import pipeline
from deeplearning4j_tpu.updaters import Adam


def _batches(n, b=8, d=12, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return [
        DataSet(rng.standard_normal((b, d)).astype(np.float32),
                np.eye(c, dtype=np.float32)[rng.integers(0, c, b)])
        for _ in range(n)
    ]


def _mlp(k=1, telemetry=None, fault_policy=None, seed=7):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .steps_per_call(k))
    if telemetry is not None:
        b = b.telemetry(telemetry)
    if fault_policy is not None:
        b = b.fault_policy(fault_policy)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def _assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("a_total")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("g")
        g.set(4)
        g.inc()
        assert g.value() == 5.0
        h = reg.histogram("h_seconds", ring_size=8)
        for v in range(16):  # ring keeps the last 8: 8..15
            h.observe(float(v))
        assert h.count == 16 and h.sum == sum(range(16))
        assert h.quantile(0.0) == 8.0
        assert h.quantile(1.0) == 15.0

    def test_get_or_create_and_type_conflict(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.counter("lbl", labels={"fn": "a"}) is not reg.counter(
            "lbl", labels={"fn": "b"})
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x")
        assert reg.get("nope") is None

    def test_callback_gauge(self):
        reg = MetricsRegistry()
        box = [1.0]
        g = reg.gauge("depth", fn=lambda: box[0])
        assert g.value() == 1.0
        box[0] = 7
        assert reg.snapshot()["depth"] == 7.0

    def test_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("req_total", "requests", labels={"code": "200"}).inc(3)
        reg.gauge("depth", "queue depth").set(2)
        h = reg.histogram("lat_seconds", "latency")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        text = reg.prometheus_text()
        assert "# TYPE req_total counter" in text
        assert 'req_total{code="200"} 3' in text
        assert "# HELP depth queue depth" in text
        assert "# TYPE lat_seconds summary" in text
        assert 'lat_seconds{quantile="0.5"}' in text
        assert "lat_seconds_count 3" in text

    def test_snapshot_labels(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", labels={"bucket": "8"}).inc(2)
        reg.counter("hits_total", labels={"bucket": "16"}).inc()
        snap = reg.snapshot()
        assert snap["hits_total"] == {"bucket=8": 2.0, "bucket=16": 1.0}


class TestServingMetricsRebase:
    def test_public_surface_unchanged(self):
        from deeplearning4j_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics(ring_size=16)
        m.record_request(4)
        m.record_dispatch(8)
        m.record_dispatch(8)
        m.record_reject()
        m.record_latency(0.010)
        m.record_latency(0.020)
        assert m.requests == 1 and m.examples == 4
        assert m.rejects == 1 and m.dispatches == 2
        assert m.bucket_hits == {8: 2}
        snap = m.snapshot(queue_depth=3)
        for key in ("requests", "examples", "rejects", "deadline_exceeded",
                    "errors", "dispatches", "reloads", "bucket_hits",
                    "uptime_s", "latency_window", "latency_p50_ms",
                    "latency_p90_ms", "latency_p99_ms", "queue_depth"):
            assert key in snap
        assert snap["latency_window"] == 2
        # original index rule: idx = min(int(q*n), n-1) → 0.5 of 2 → [1]
        assert m.latency_quantile(0.5) == 0.020
        text = m.prometheus_text()
        assert "serving_requests_total 1" in text
        assert 'serving_bucket_hits_total{bucket="8"} 2' in text

    def test_instances_are_isolated_by_default(self):
        from deeplearning4j_tpu.serving.metrics import ServingMetrics

        a, b = ServingMetrics(), ServingMetrics()
        a.record_request(1)
        assert a.requests == 1 and b.requests == 0


# ---------------------------------------------------------------------------
# in-graph telemetry
# ---------------------------------------------------------------------------
class TestTelemetryParity:
    def test_k4_bit_identical_params_and_adam_slots(self):
        """The acceptance backbone: telemetry-enabled training must be
        BIT-identical to telemetry-off at K=4 — params AND Adam slots
        (the m/v moments + bias-correction clock)."""
        data = _batches(10)
        a = _mlp(4)
        b = _mlp(4, telemetry=True)
        a.fit(ExistingDataSetIterator(data), epochs=2)
        b.fit(ExistingDataSetIterator(data), epochs=2)
        assert a.iteration == b.iteration == 20
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)

    def test_guarded_k4_bit_identical(self):
        """Same under a FaultPolicy (telemetry then also reports loss
        scale/bad count from the fault state)."""
        data = _batches(8)
        a = _mlp(4, fault_policy=True)
        b = _mlp(4, telemetry=True, fault_policy=True)
        a.fit(ExistingDataSetIterator(data), epochs=1)
        b.fit(ExistingDataSetIterator(data), epochs=1)
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)

    def test_per_step_values_match_k1(self):
        """Bundled telemetry is exact per-step: grad norms of a K=4 fit
        equal the K=1 fit's, step by step."""
        class Capture:
            def __init__(self):
                self.rows = {}

            def telemetry_done(self, model, it0, epoch, telem):
                host = telem.host()
                for j in range(len(telem)):
                    self.rows[it0 + j + 1] = {k: float(v[j])
                                              for k, v in host.items()}

            def iteration_done(self, model, iteration, epoch):
                pass

        data = _batches(8)
        caps = []
        for k in (1, 4):
            net = _mlp(k, telemetry=True)
            cap = Capture()
            net.set_listeners(cap)
            net.fit(ExistingDataSetIterator(data), epochs=1)
            caps.append(cap.rows)
        assert set(caps[0]) == set(caps[1]) == set(range(1, 9))
        for it in caps[0]:
            for key in ("grad_norm", "param_norm", "update_norm",
                        "update_ratio"):
                assert caps[0][it][key] == caps[1][it][key], (it, key)

    def test_skipped_step_reports_zero_update(self):
        """update norm comes from the ACTUAL post-skip delta: a NaN step
        under the guard must report update_norm == 0."""
        from deeplearning4j_tpu.train import faults

        class Capture:
            rows = {}

            def telemetry_done(self, model, it0, epoch, telem):
                host = telem.host()
                for j in range(len(telem)):
                    self.rows[it0 + j + 1] = {k: float(v[j])
                                              for k, v in host.items()}

            def iteration_done(self, model, iteration, epoch):
                pass

        data = _batches(4)
        with faults.fault_injection(nan_grad_steps=[2]):
            net = _mlp(4, telemetry=True, fault_policy=True)
            cap = Capture()
            net.set_listeners(cap)
            net.fit(ExistingDataSetIterator(data), epochs=1)
        # injection keys on the 0-based iteration ARGUMENT (=2), which is
        # the bundle's third step → host row it0+j+1 == 3
        assert cap.rows[3]["update_norm"] == 0.0
        assert cap.rows[3]["bad_count"] == 1.0
        assert cap.rows[2]["update_norm"] > 0.0
        assert cap.rows[2]["bad_count"] == 0.0
        assert cap.rows[4]["update_norm"] > 0.0
        assert cap.rows[4]["bad_count"] == 1.0

    def test_conf_serde_roundtrip(self):
        conf = _mlp(2, telemetry=TelemetryConf(update_ratio=False)).conf
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

        again = MultiLayerConfiguration.from_json(conf.to_json())
        assert again.global_conf.telemetry == TelemetryConf(
            update_ratio=False)
        assert again.to_json() == conf.to_json()


class TestTelemetryFetchDiscipline:
    def test_one_fetch_per_bundle_with_stats_listener(self, monkeypatch):
        """The sync-free regression for the monitoring path: a bundled
        fit with a StatsListener attached fetches the stacked scores at
        most once per bundle AND the stacked telemetry at most once per
        bundle — and never calls model.score()."""
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

        data = _batches(8)
        net = _mlp(4, telemetry=True)
        net.set_listeners(StatsListener(InMemoryStatsStorage(),
                                        reporting_frequency=1,
                                        session_id="fetch"))

        def banned_score(ds=None):
            raise AssertionError("model.score() sync inside a bundled fit")

        monkeypatch.setattr(net, "score", banned_score)
        s0, t0 = pipeline._host_fetches, obs_telemetry._host_fetches
        net.fit(ExistingDataSetIterator(data), epochs=1)
        assert pipeline._host_fetches - s0 == 2  # one per bundle
        assert obs_telemetry._host_fetches - t0 == 2  # one per bundle

    def test_stats_records_carry_per_step_telemetry(self):
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

        storage = InMemoryStatsStorage()
        data = _batches(8)
        net = _mlp(4, telemetry=True)
        net.set_listeners(StatsListener(storage, reporting_frequency=2,
                                        session_id="t"))
        assert pipeline.resolve_steps_per_call(net) == 4
        net.fit(ExistingDataSetIterator(data), epochs=1)
        recs = [r for r in storage.get_records("t") if r["kind"] == "update"]
        assert [r["iteration"] for r in recs] == [1, 2, 4, 6, 8]
        for r in recs:
            assert {"grad_norm", "param_norm", "update_norm",
                    "update_ratio"} <= set(r["telemetry"])
        # param summaries at bundle granularity, marked
        with_params = [r for r in recs if "parameters" in r]
        assert [r["params_at_iteration"] for r in with_params] == [4, 8]

    def test_metrics_listener_publishes(self):
        reg = MetricsRegistry()
        data = _batches(8)
        net = _mlp(4, telemetry=True)
        net.add_listeners(MetricsListener(registry=reg, frequency=4))
        net.fit(ExistingDataSetIterator(data), epochs=1)
        snap = reg.snapshot()
        assert snap["train_steps_total"] == 8.0
        assert snap["train_samples_total"] == 64.0
        assert snap["train_epochs_total"] == 1.0
        assert snap["train_grad_norm"] > 0.0
        assert snap["train_update_ratio"] > 0.0
        assert snap["train_loss"] > 0.0


class TestBundlingLegalityAfterTelemetry:
    def test_pgil_modes(self):
        from deeplearning4j_tpu.train.listeners import (
            ParamAndGradientIterationListener,
        )

        per_param = ParamAndGradientIterationListener(
            output_to_console=False)
        assert pipeline.bundling_blockers([per_param]) == [
            "ParamAndGradientIterationListener.on_gradient_calculation"]
        telem = ParamAndGradientIterationListener(
            output_to_console=False, gradients="telemetry")
        assert pipeline.bundling_blockers([telem]) == []
        none = ParamAndGradientIterationListener(
            output_to_console=False, gradients="none")
        assert pipeline.bundling_blockers([none]) == []
        with pytest.raises(ValueError, match="gradients"):
            ParamAndGradientIterationListener(gradients="bogus")

    def test_pgil_telemetry_mode_writes_per_step_rows(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import (
            ParamAndGradientIterationListener,
        )

        path = str(tmp_path / "pg.tsv")
        data = _batches(8)
        net = _mlp(4, telemetry=True)
        net.set_listeners(ParamAndGradientIterationListener(
            iterations=1, output_to_console=False, file=path,
            gradients="telemetry"))
        assert pipeline.resolve_steps_per_call(net) == 4
        net.fit(ExistingDataSetIterator(data), epochs=1)
        lines = open(path).read().strip().split("\n")
        header = lines[0].split("\t")
        assert header[0] == "iteration" and "grad_norm" in header
        assert len(lines) == 1 + 8  # header + one row per step
        assert [int(r.split("\t")[0]) for r in lines[1:]] == list(range(1, 9))


# ---------------------------------------------------------------------------
# retrace monitor — the CI recompile guard
# ---------------------------------------------------------------------------
class TestRetraceMonitor:
    def test_count_retraces_counts_traces_not_calls(self):
        reg = MetricsRegistry()

        def f(x):
            return x * 2

        jf = jax.jit(obs_trace.count_retraces("f", f, registry=reg))
        jf(np.zeros((2,), np.float32))
        jf(np.ones((2,), np.float32))  # cache hit
        assert obs_trace.retrace_counts(reg) == {"f": 1.0}
        jf(np.zeros((3,), np.float32))  # new shape → retrace
        assert obs_trace.retrace_counts(reg) == {"f": 2.0}

    def test_k16_fit_zero_steady_state_recompiles(self):
        """The guard future PRs must not trip: after a warm epoch, a
        K=16 bundled fit (with telemetry + StatsListener attached, i.e.
        monitoring ON) compiles NOTHING in steady state."""
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

        data = _batches(32)
        net = _mlp(16, telemetry=True)
        net.set_listeners(StatsListener(InMemoryStatsStorage(),
                                        reporting_frequency=8,
                                        session_id="guard"))
        net.fit(ExistingDataSetIterator(data), epochs=1)  # warm: compiles
        with obs_trace.RetraceMonitor() as mon:
            net.fit(ExistingDataSetIterator(data), epochs=2)
        assert mon.total() == 0, (
            f"steady-state recompiles detected: {mon.delta()}")

    def test_serving_storm_zero_recompiles(self):
        """Bucketed serving keeps the PR-3 discipline, now visible in
        the registry: warmup compiles every bucket, a mixed-size storm
        compiles nothing."""
        from deeplearning4j_tpu.serving.buckets import BucketPolicy
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        net = _mlp()
        eng = InferenceEngine(net, buckets=BucketPolicy(batch_buckets=[4, 8]))
        eng.warmup(example_shape=(12,))
        reg = eng.metrics.registry
        with obs_trace.RetraceMonitor(reg) as mon:
            rng = np.random.default_rng(0)
            for n in (1, 3, 4, 5, 8, 2, 7, 8, 1):
                eng.infer(rng.standard_normal((n, 12)).astype(np.float32))
        assert mon.total() == 0, mon.delta()
        assert obs_trace.retrace_counts(reg)["serving_forward"] == \
            eng.compile_count


# ---------------------------------------------------------------------------
# exporter + serving surfaces
# ---------------------------------------------------------------------------
class TestExporter:
    def test_negotiation_rule(self):
        assert wants_prometheus("text/plain;version=0.0.4")
        assert wants_prometheus("application/openmetrics-text")
        assert not wants_prometheus("application/json")
        assert not wants_prometheus("")
        assert wants_prometheus("application/json", "format=prometheus")
        assert not wants_prometheus("text/plain", "format=json")

    def test_http_endpoint(self):
        reg = MetricsRegistry()
        reg.counter("train_steps_total").inc(5)
        srv = MetricsServer(registry=reg, port=0).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            conn.request("GET", "/metrics")
            r = conn.getresponse()
            assert r.status == 200
            assert "application/json" in r.getheader("Content-Type")
            assert json.loads(r.read())["train_steps_total"] == 5.0
            conn.request("GET", "/metrics",
                         headers={"Accept": "text/plain"})
            r = conn.getresponse()
            assert r.status == 200
            assert "text/plain" in r.getheader("Content-Type")
            assert b"train_steps_total 5" in r.read()
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200 and json.loads(r.read())["status"] == "ok"
            conn.request("GET", "/nope")
            r = conn.getresponse()
            assert r.status == 404
            r.read()
        finally:
            srv.shutdown()


class TestServingSurfaces:
    @pytest.fixture()
    def server(self):
        from deeplearning4j_tpu.serving.buckets import BucketPolicy
        from deeplearning4j_tpu.serving.engine import InferenceEngine
        from deeplearning4j_tpu.serving.server import InferenceServer

        eng = InferenceEngine(_mlp(),
                              buckets=BucketPolicy(batch_buckets=[4]))
        eng.warmup(example_shape=(12,))
        srv = InferenceServer(eng, port=0).start()
        yield srv
        srv.shutdown()

    def test_healthz_canary_keys(self, server):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200
        body = json.loads(r.read())
        assert body["status"] == "ok"
        assert body["snapshot_version"] == 0
        assert "checkpoint_fingerprint" in body  # None for init engines
        assert body["uptime_s"] >= 0

    def test_metrics_content_negotiation(self, server):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("POST", "/predict",
                     json.dumps({"inputs": [[0.0] * 12]}))
        r = conn.getresponse()
        assert r.status == 200
        r.read()
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        assert "application/json" in r.getheader("Content-Type")
        snap = json.loads(r.read())
        assert "requests" in snap and "queue_depth" in snap
        conn.request("GET", "/metrics",
                     headers={"Accept": "text/plain;version=0.0.4"})
        r = conn.getresponse()
        assert "text/plain" in r.getheader("Content-Type")
        text = r.read().decode()
        assert "serving_requests_total" in text
        assert "serving_queue_depth" in text


# ---------------------------------------------------------------------------
# listener satellites
# ---------------------------------------------------------------------------
class TestPerformanceListenerAccounting:
    def test_variable_batch_sizes_accumulate(self):
        """samples/sec must reflect the ACTUAL per-step sizes: with a
        ragged tail (8,8,8,2 after the window opens) the ratio
        samples_per_sec / batches_per_sec — the dt cancels — is the true
        mean batch size, not the last one extrapolated."""
        from deeplearning4j_tpu.train.listeners import PerformanceListener

        class Model:
            last_batch_size = 0

            def score(self):
                return 0.0

        out = []
        lst = PerformanceListener(frequency=4, printer=out.append)
        m = Model()
        sizes = [8, 8, 8, 8, 2]  # first call opens the window
        for i, bs in enumerate(sizes, start=1):
            m.last_batch_size = bs
            lst.iteration_done(m, i, 0)
        assert len(out) == 1
        mean_bs = (lst.last_samples_per_sec / lst.last_batches_per_sec)
        assert mean_bs == pytest.approx((8 + 8 + 8 + 2) / 4)

    def test_bundle_path_uses_bundle_sizes(self):
        from deeplearning4j_tpu.train.listeners import PerformanceListener

        class Scores:
            def __init__(self, k):
                self.k = k

            def __len__(self):
                return self.k

        class Model:
            last_batch_size = 8

        out = []
        lst = PerformanceListener(frequency=4, printer=out.append)
        m = Model()
        lst.bundle_done(m, 0, 0, Scores(4))   # opens window
        m.last_batch_size = 4
        lst.bundle_done(m, 4, 0, Scores(4))   # 4 steps × batch 4
        assert len(out) == 1
        assert (lst.last_samples_per_sec / lst.last_batches_per_sec
                == pytest.approx(4.0))


class TestProfilerListenerFitExit:
    def test_closes_open_window_at_fit_exit(self, tmp_path):
        """A window spanning past the data (start=1, 999 iterations on a
        4-batch fit) used to leak an open trace; fit() exit closes it."""
        from deeplearning4j_tpu.train.listeners import ProfilerListener

        lst = ProfilerListener(str(tmp_path), start_iteration=1,
                               num_iterations=999)
        net = _mlp()
        net.set_listeners(lst)
        net.fit(ExistingDataSetIterator(_batches(4)), epochs=1)
        assert lst.completed and not lst._active
        # the profiler is actually released: a fresh trace can start
        jax.profiler.start_trace(str(tmp_path / "again"))
        jax.profiler.stop_trace()

    def test_closes_on_mid_epoch_exception(self, tmp_path):
        from deeplearning4j_tpu.data.iterators import DataSetIterator
        from deeplearning4j_tpu.train.listeners import ProfilerListener

        class Poisoned(DataSetIterator):
            def __init__(self, batches):
                self._b = list(batches)
                self._i = 0

            def has_next(self):
                return True

            def next(self):
                if self._i >= 2:
                    raise RuntimeError("boom mid-epoch")
                self._i += 1
                return self._b[self._i - 1]

            def reset(self):
                self._i = 0

            def async_supported(self):
                return False

            def batch(self):
                return 8

        lst = ProfilerListener(str(tmp_path), start_iteration=1,
                               num_iterations=999)
        net = _mlp()
        net.set_listeners(lst)
        with pytest.raises(RuntimeError, match="boom"):
            net.fit(Poisoned(_batches(4)), epochs=1)
        assert lst.completed and not lst._active
        jax.profiler.start_trace(str(tmp_path / "again"))
        jax.profiler.stop_trace()


class TestDataPipelineGauges:
    def test_consumer_wait_counter_grows_on_slow_producer(self):
        class Slow(ExistingDataSetIterator):
            def next(self):
                time.sleep(0.02)
                return super().next()

        _, before = data_wait_seconds()
        it = AsyncDataSetIterator(Slow(_batches(6)), queue_size=2)
        while it.has_next():
            it.next()
        it.shutdown()
        _, after = data_wait_seconds()
        assert after > before  # fit loop waited on the empty queue

    def test_producer_wait_counter_grows_on_slow_consumer(self):
        before, _ = data_wait_seconds()
        it = AsyncDataSetIterator(ExistingDataSetIterator(_batches(8)),
                                  queue_size=1)
        time.sleep(0.3)  # producer fills the depth-1 queue and blocks
        while it.has_next():
            it.next()
        it.shutdown()
        after, _ = data_wait_seconds()
        assert after > before
