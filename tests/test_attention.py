"""Attention + ring attention tests (new capability; no reference analog —
SURVEY.md §5 long-context mandate). Ring attention is validated against
dense attention on the 8-device CPU mesh."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
from deeplearning4j_tpu.nn.conf.layers.attention import (
    LayerNormalization,
    PositionalEmbeddingLayer,
    SelfAttentionLayer,
    TransformerBlock,
    dense_attention,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.parallel.ring_attention import make_ring_attention
from deeplearning4j_tpu.updaters import Adam


class TestDenseAttention:
    def test_causal_masking(self):
        rng = jax.random.PRNGKey(0)
        q = jax.random.normal(rng, (2, 2, 6, 4))
        out_full = dense_attention(q, q, q, causal=True)
        # causal: output at position t must not change if future positions change
        q2 = q.at[:, :, 4:, :].set(999.0)
        out_pref = dense_attention(q2, q2, q2, causal=True)
        np.testing.assert_allclose(
            np.asarray(out_full[:, :, :4]), np.asarray(out_pref[:, :, :4]),
            rtol=1e-5, atol=1e-6,
        )

    def test_key_padding_mask(self):
        rng = jax.random.PRNGKey(1)
        x = jax.random.normal(rng, (1, 1, 4, 4))
        mask = jnp.asarray([[1.0, 1.0, 0.0, 0.0]])
        out = dense_attention(x, x, x, causal=False, mask=mask)
        # masked keys contribute nothing: recompute with only first 2 positions
        out2 = dense_attention(x[:, :, :, :], x[:, :, :2, :], x[:, :, :2, :],
                               causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   rtol=1e-5, atol=1e-6)


class TestBlockedAttention:
    """Long-sequence XLA fallback (VERDICT r3 item 4): the scan-blocked
    formulation must equal the materialized dense computation exactly —
    values AND gradients — for causal and key-masked variants."""

    def _qkv(self, T=1024, hd=8):
        rng = np.random.default_rng(3)
        mk = lambda: jnp.asarray(rng.standard_normal((1, 2, T, hd)),
                                 jnp.float32)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_materialized_dense(self, causal):
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            _blocked_attention,
        )

        q, k, v = self._qkv()
        scale = 1.0 / math.sqrt(q.shape[-1])

        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            if causal:
                tri = jnp.tril(jnp.ones((q.shape[2],) * 2, bool))
                s = jnp.where(tri, s, -1e30)
            return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

        def blocked(q, k, v):
            return _blocked_attention(q, k, v, causal=causal, mask=None,
                                      scale=scale, block_q=256)

        np.testing.assert_allclose(np.asarray(blocked(q, k, v)),
                                   np.asarray(dense(q, k, v)),
                                   rtol=2e-5, atol=2e-5)
        loss = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        gb = jax.grad(loss(blocked), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss(dense), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gb, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name} diverged")

    def test_key_mask_and_routing(self):
        from deeplearning4j_tpu.nn.conf.layers import attention as att

        q, k, v = self._qkv()
        mask = jnp.asarray(
            (np.arange(1024) < 700).astype(np.float32))[None, :]
        got = att._blocked_attention(q, k, v, causal=False, mask=mask,
                                     scale=q.shape[-1] ** -0.5, block_q=512)
        want = att.dense_attention(q[:, :, :, :], k[:, :, :700, :],
                                   v[:, :, :700, :], causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # dense_attention routes T>=1024 through the blocked path (no
        # (T,T) materialization); same numbers either way
        via_router = att.dense_attention(q, k, v, causal=False, mask=mask)
        np.testing.assert_allclose(np.asarray(via_router), np.asarray(got),
                                   rtol=1e-6, atol=1e-6)


class TestSelfAttentionLayer:
    def _net(self, causal=False, T=8, d=12):
        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .list()
            .layer(PositionalEmbeddingLayer(max_length=T))
            .layer(SelfAttentionLayer(n_heads=3, causal=causal))
            .layer(RnnOutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(d))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    def test_shapes_and_training(self):
        net = self._net()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, (4, 8))]
        net.fit(DataSet(x, y), epochs=3)
        out = net.output(x)
        assert out.shape == (4, 8, 5)
        assert np.isfinite(net.score(DataSet(x, y)))

    def test_mask_zeroes_padded_positions(self):
        net = self._net()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 8, 12)).astype(np.float32)
        fm = np.ones((2, 8), np.float32)
        fm[:, 6:] = 0.0
        # attention layer output at valid positions must ignore padded keys
        out_m = net.output(x, mask=fm)
        x2 = x.copy()
        x2[:, 6:, :] = 123.0  # junk in padded region
        out_m2 = net.output(x2, mask=fm)
        np.testing.assert_allclose(out_m[:, :6], out_m2[:, :6], rtol=1e-4, atol=1e-5)


class TestTransformerBlock:
    def test_learns_copy_task(self):
        """Tiny LM-style task: predict the token at the same position
        (identity over a causal block → learnable)."""
        V, T, d = 7, 6, 16
        conf = (
            NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
            .list()
            .layer(PositionalEmbeddingLayer(max_length=T))
            .layer(TransformerBlock(n_heads=4, causal=True))
            .layer(TransformerBlock(n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=V, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(d))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (64, T))
        # input: one-hot in first V dims of d
        x = np.zeros((64, T, d), np.float32)
        x[np.arange(64)[:, None], np.arange(T)[None, :], ids] = 1.0
        y = np.eye(V, dtype=np.float32)[ids]
        s0 = net.score(DataSet(x, y))
        net.fit(DataSet(x, y), epochs=30, batch_size=32)
        s1 = net.score(DataSet(x, y))
        assert s1 < s0 * 0.5, f"transformer should learn copy task: {s0} -> {s1}"

    def test_serde(self):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        conf = (
            NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(TransformerBlock(n_heads=2, causal=True, mlp_ratio=2))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(8))
            .build()
        )
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        blk = conf2.layers[0]
        assert isinstance(blk, TransformerBlock)
        assert blk.n_heads == 2 and blk.causal and blk.mlp_ratio == 2


class TestRingAttention:
    """Ring == dense, on the 8-device CPU mesh (seq axis)."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("seq_devices", [2, 4, 8])
    def test_matches_dense(self, causal, seq_devices):
        mesh = TrainingMesh(data=1, seq=seq_devices,
                            devices=jax.devices()[:seq_devices])
        rng = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(rng, 3)
        b, h, T, hd = 2, 3, 16, 8
        q = jax.random.normal(kq, (b, h, T, hd))
        k = jax.random.normal(kk, (b, h, T, hd))
        v = jax.random.normal(kv, (b, h, T, hd))
        ring = make_ring_attention(mesh)
        out_ring = ring(q, k, v, causal=causal)
        out_dense = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                                   rtol=2e-4, atol=2e-5)

    def test_matches_dense_with_mask(self):
        mesh = TrainingMesh(data=1, seq=4, devices=jax.devices()[:4])
        rng = jax.random.PRNGKey(7)
        b, h, T, hd = 2, 2, 16, 4
        q = jax.random.normal(rng, (b, h, T, hd))
        mask = (jax.random.uniform(jax.random.PRNGKey(8), (b, T)) > 0.3).astype(
            jnp.float32
        )
        mask = mask.at[:, 0].set(1.0)  # every example keeps >= 1 key
        ring = make_ring_attention(mesh)
        out_ring = ring(q, q, q, causal=False, mask=mask)
        out_dense = dense_attention(q, q, q, causal=False, mask=mask)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_long_sequence_grad_flows(self):
        """Gradients flow through the ring (autodiff over ppermute)."""
        mesh = TrainingMesh(data=1, seq=4, devices=jax.devices()[:4])
        ring = make_ring_attention(mesh)
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, 4))

        def loss(q):
            return jnp.sum(ring(q, q, q, causal=True) ** 2)

        g = jax.grad(loss)(q)
        assert np.all(np.isfinite(np.asarray(g)))
        # compare to dense gradient
        def loss_d(q):
            return jnp.sum(dense_attention(q, q, q, causal=True) ** 2)

        gd = jax.grad(loss_d)(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gd), rtol=1e-3,
                                   atol=1e-4)


class TestAdvisorRegressions:
    """Round-1 advisor findings (ADVICE.md) pinned by tests."""

    def test_attention_dropout_applies_to_probabilities(self):
        """Dropout must act on the softmax probability matrix, not the
        weighted sum: with constant values v=c every undropped prob row
        still mixes to a multiple of c, so output stays in span{c} — the
        old (wrong) post-sum dropout produced exact zero entries."""
        rng = jax.random.PRNGKey(3)
        b, h, T, d = 2, 2, 6, 4
        q = jax.random.normal(jax.random.PRNGKey(1), (b, h, T, d))
        k = jax.random.normal(jax.random.PRNGKey(2), (b, h, T, d))
        c = jnp.arange(1.0, d + 1)  # constant value vector per key
        v = jnp.broadcast_to(c, (b, h, T, d))
        out = dense_attention(q, k, v, causal=False,
                              dropout_rate=0.5, dropout_rng=rng)
        # every output row must be a (possibly zero) scalar multiple of c
        ratio = out / c
        spread = jnp.abs(ratio - ratio.mean(-1, keepdims=True)).max()
        assert float(spread) < 1e-5
        # and dropout actually does something (different from no-dropout)
        base = dense_attention(q, k, v, causal=False)
        assert not np.allclose(np.asarray(out), np.asarray(base))

    def test_sinusoidal_positional_embedding_odd_dim(self):
        layer = PositionalEmbeddingLayer(mode="sinusoidal")
        it = InputType.recurrent(5, 3)  # odd feature dim
        layer.initialize(it)
        p = layer.init_params(jax.random.PRNGKey(0), it)
        x = jnp.zeros((2, 3, 5))
        y, _ = layer.apply(p, x)
        assert y.shape == (2, 3, 5)
        assert np.all(np.isfinite(np.asarray(y)))

    def test_generate_windows_context_past_max_length(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        lm = TransformerLM(vocab_size=17, d_model=8, n_heads=2, n_layers=1,
                           max_length=8).init()
        prompt = np.arange(6, dtype=np.int32)
        out = lm.generate(prompt, max_new=8)  # grows to 14 > max_length=8
        assert out.shape == (1, 14)
        assert np.all(out < 17)

    @pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
    def test_fused_qkv_bitwise_identical(self, compute_dtype):
        """fused_qkv computes Q,K,V as one (d, 3d) dot: every output
        column block sees only its own weight block, so logits must be
        BITWISE identical to the three-dot layout (param layout/
        checkpoints/TP pspecs unchanged)."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(
            np.int32)
        outs = []
        for fq in (False, True):
            m = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, max_length=32,
                              compute_dtype=compute_dtype,
                              fused_qkv=fq).init()
            outs.append(np.asarray(m.logits(ids)))
        np.testing.assert_array_equal(outs[0], outs[1])


class TestFlashAttentionGate:
    def test_gate_logic(self, monkeypatch):
        """Pallas flash attention only engages on TPU with block-aligned
        unmasked shapes (parity itself is verified on real TPU hardware
        by the round's verify drive: fwd/grad err ~1e-6)."""
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            _flash_attention_route,
        )

        q = jnp.zeros((2, 4, 512, 128))
        # CPU backend in tests → never eligible
        assert _flash_attention_route(q, q, True, None, 0.0) is None
        # kill switch + disqualifiers are independent of backend
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "0")
        assert _flash_attention_route(q, q, True, None, 0.0) is None
        monkeypatch.delenv("DL4J_TPU_FLASH_ATTENTION")
        assert _flash_attention_route(q, q, True, jnp.ones((2, 512)),
                                      0.0) is None
        assert _flash_attention_route(q, q, True, None, 0.1) is None
        q_bad = jnp.zeros((2, 4, 100, 128))
        assert _flash_attention_route(q_bad, q_bad, True, None, 0.0) is None
        # cross-attention with mismatched kv length stays dense
        k_short = jnp.zeros((2, 4, 256, 128))
        assert _flash_attention_route(q, k_short, True, None, 0.0) is None

    def test_compile_probe_failure_falls_back_and_caches(self, monkeypatch):
        """A Mosaic/toolchain mismatch (e.g. the axon server-side libtpu
        rejecting bf16 tpu.matmul: "Bad lhs type") must disable the flash
        path for that instantiation instead of failing the model step.
        The probe result is cached per (dtype, seq, head_dim, causal)."""
        import deeplearning4j_tpu.nn.conf.layers.attention as A

        monkeypatch.setattr(A, "_FLASH_PROBE_CACHE", {})
        compiles = {"n": 0}

        class _Boom:
            def lower(self, *a, **k):
                return self

            def compile(self):
                compiles["n"] += 1
                raise RuntimeError("Mosaic failed to compile TPU kernel: "
                                   "Bad lhs type")

        monkeypatch.setattr(jax, "jit", lambda *a, **k: _Boom())
        assert A._flash_attention_impl(jnp.bfloat16, 512, 64, True) is None
        assert A._FLASH_PROBE_CACHE == {
            ("bfloat16", 512, 64, True, False): None}
        # both the in-tree and the jax-bundled kernel were attempted
        assert compiles["n"] == 2
        # second call hits the cache: no further compile attempts
        assert A._flash_attention_impl(jnp.bfloat16, 512, 64, True) is None
        assert compiles["n"] == 2
        # a different instantiation re-probes
        assert A._flash_attention_impl(jnp.bfloat16, 1024, 128, True) is None
        assert compiles["n"] == 4

    def test_compile_probe_success_prefers_own_kernel(self, monkeypatch):
        import deeplearning4j_tpu.nn.conf.layers.attention as A

        monkeypatch.setattr(A, "_FLASH_PROBE_CACHE", {})
        monkeypatch.setattr(A, "_probe_compiles",
                            lambda *a, **k: True)
        impl = A._flash_attention_impl(jnp.float32, 128, 128, False)
        assert callable(impl)
        assert A._FLASH_PROBE_CACHE[
            ("float32", 128, 128, False, False)] is impl
        # the chosen impl is the in-tree kernel (probed first)
        from deeplearning4j_tpu.nn.ops.flash_attention import flash_attention
        assert impl.args[0] is flash_attention

    def test_segment_probe_only_tries_in_tree_kernel(self, monkeypatch):
        """has_seg probes cache under their own key, and the jax-bundled
        kernel (different segment API) is never a candidate."""
        import deeplearning4j_tpu.nn.conf.layers.attention as A

        from deeplearning4j_tpu.nn.ops.registry import (
            default_kernel_registry,
        )

        monkeypatch.setattr(A, "_FLASH_PROBE_CACHE", {})
        default_kernel_registry().reset("flash_attention")
        attempted = []

        def probe(fn, *a, **k):
            attempted.append(fn)
            raise RuntimeError("probe reject")  # registry contract:
            # a failing probe RAISES (deterministic → one attempt)

        monkeypatch.setattr(A, "_probe_compiles", probe)
        try:
            assert A._flash_attention_impl(jnp.float32, 256, 64, True,
                                           has_seg=True) is None
            assert ("float32", 256, 64, True, True) in A._FLASH_PROBE_CACHE
            assert len(attempted) == 1  # in-tree only; bundled skipped
        finally:
            default_kernel_registry().reset("flash_attention")

    def test_seq_beyond_own_kernel_cap_tries_bundled(self, monkeypatch):
        """T past the in-tree kernel's MAX_SEQ_LEN must skip it (no
        probe) and try the jax-bundled kernel."""
        import deeplearning4j_tpu.nn.conf.layers.attention as A
        from deeplearning4j_tpu.nn.ops.flash_attention import MAX_SEQ_LEN

        monkeypatch.setattr(A, "_FLASH_PROBE_CACHE", {})
        monkeypatch.setattr(A, "_probe_compiles",
                            lambda *a, **k: True)
        impl = A._flash_attention_impl(jnp.bfloat16, MAX_SEQ_LEN * 2, 128,
                                       True)
        assert callable(impl)
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )
        assert impl.args[0] is jax_flash

    def test_value_check_rejects_wrong_kernel(self):
        """The probe must EXECUTE the kernel and compare against the
        dense reference — a kernel that compiles but miscomputes (a
        lagging Mosaic can miscompile, not just reject) is refused."""
        import deeplearning4j_tpu.nn.conf.layers.attention as A

        with pytest.raises(RuntimeError, match="value check failed"):
            A._probe_compiles(lambda q, k, v: jnp.zeros_like(q), 128, 64,
                              jnp.float32, False)

    def test_value_check_accepts_correct_kernel(self):
        """A numerically correct implementation passes the value check
        (here: the in-tree Pallas kernel in interpreter mode)."""
        import deeplearning4j_tpu.nn.conf.layers.attention as A
        from deeplearning4j_tpu.nn.ops.flash_attention import flash_attention

        assert A._probe_compiles(
            lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            sm_scale=64 ** -0.5,
                                            interpret=True),
            128, 64, jnp.float32, True)
