"""RecordReader bridge + fetcher tests (reference
``RecordReaderDataSetiteratorTest.java`` 1,301 LoC patterns: CSV
classification/regression, image directory, sequence alignment + masks,
and a RecordReader-driven training run; SURVEY.md §4.4).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    ALIGN_END,
    CSVRecordReader,
    CollectionRecordReader,
    ImageRecordReader,
    RecordReaderDataSetIterator,
    SequenceRecordReader,
    SequenceRecordReaderDataSetIterator,
    SvhnDataSetIterator,
    TinyImageNetDataSetIterator,
    UciSequenceDataSetIterator,
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "iris_like.csv"
    rng = np.random.default_rng(0)
    lines = ["a,b,c,label"]
    for _ in range(40):
        cls = rng.integers(0, 3)
        vals = rng.standard_normal(3) + cls
        lines.append(",".join(f"{v:.4f}" for v in vals) + f",{cls}")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestCSV:
    def test_classification_mode(self, csv_file):
        rr = CSVRecordReader(csv_file, skip_num_lines=1)
        it = RecordReaderDataSetIterator(rr, 16, label_index=3,
                                         num_possible_labels=3)
        ds = it.next()
        assert ds.features.shape == (16, 3)
        assert ds.labels.shape == (16, 3)
        assert np.all(ds.labels.sum(1) == 1)  # one-hot
        total = 16
        while it.has_next():
            total += it.next().features.shape[0]
        assert total == 40
        it.reset()
        assert it.has_next()

    def test_regression_mode(self, csv_file):
        rr = CSVRecordReader(csv_file, skip_num_lines=1)
        it = RecordReaderDataSetIterator(rr, 8, regression=True,
                                         label_index_from=1,
                                         label_index_to=2)
        ds = it.next()
        assert ds.features.shape == (8, 2)  # cols a, label
        assert ds.labels.shape == (8, 2)   # cols b, c

    def test_collection_reader(self):
        recs = [[0.1, 0.2, 1], [0.3, 0.4, 0]]
        it = RecordReaderDataSetIterator(
            CollectionRecordReader(recs), 2, label_index=2,
            num_possible_labels=2,
        )
        ds = it.next()
        np.testing.assert_allclose(ds.features,
                                   [[0.1, 0.2], [0.3, 0.4]], atol=1e-6)
        np.testing.assert_array_equal(ds.labels, [[0, 1], [1, 0]])


class TestImages:
    def test_image_directory(self, tmp_path):
        from PIL import Image

        rng = np.random.default_rng(1)
        for label in ("cats", "dogs"):
            d = tmp_path / label
            d.mkdir()
            for i in range(3):
                arr = (rng.random((10, 12, 3)) * 255).astype(np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
        rr = ImageRecordReader(8, 8, 3, str(tmp_path))
        assert rr.labels == ["cats", "dogs"]
        it = RecordReaderDataSetIterator(rr, 4, num_possible_labels=2)
        ds = it.next()
        assert ds.features.shape == (4, 8, 8, 3)
        assert ds.features.max() <= 1.0
        assert ds.labels.shape == (4, 2)


class TestSequences:
    def _write_seqs(self, tmp_path, lengths, cols=2, labels=True):
        fdir = tmp_path / "feat"
        ldir = tmp_path / "lab"
        fdir.mkdir()
        ldir.mkdir()
        rng = np.random.default_rng(2)
        for i, T in enumerate(lengths):
            f = "\n".join(
                ",".join(f"{v:.3f}" for v in rng.standard_normal(cols))
                for _ in range(T)
            )
            (fdir / f"{i:02d}.csv").write_text(f + "\n")
            l = "\n".join(str(rng.integers(0, 3)) for _ in range(T))
            (ldir / f"{i:02d}.csv").write_text(l + "\n")
        return str(fdir), str(ldir)

    def test_equal_length(self, tmp_path):
        fdir, ldir = self._write_seqs(tmp_path, [5, 5, 5])
        it = SequenceRecordReaderDataSetIterator(
            SequenceRecordReader(fdir), SequenceRecordReader(ldir),
            batch_size=3, num_possible_labels=3,
        )
        ds = it.next()
        assert ds.features.shape == (3, 5, 2)
        assert ds.labels.shape == (3, 5, 3)
        assert ds.features_mask is None

    def test_align_end_masks(self, tmp_path):
        fdir, ldir = self._write_seqs(tmp_path, [3, 5, 4])
        it = SequenceRecordReaderDataSetIterator(
            SequenceRecordReader(fdir), SequenceRecordReader(ldir),
            batch_size=3, num_possible_labels=3, alignment_mode=ALIGN_END,
        )
        ds = it.next()
        assert ds.features.shape == (3, 5, 2)
        # shorter sequences are right-aligned: first rows masked out
        np.testing.assert_array_equal(ds.features_mask[0], [0, 0, 1, 1, 1])
        np.testing.assert_array_equal(ds.features_mask[1], [1, 1, 1, 1, 1])
        np.testing.assert_array_equal(ds.features_mask[2], [0, 1, 1, 1, 1])
        assert np.all(ds.features[0, :2] == 0)

    def test_single_reader_label_column(self, tmp_path):
        fdir = tmp_path / "joint"
        fdir.mkdir()
        (fdir / "a.csv").write_text("0.1,0.2,1\n0.3,0.4,2\n")
        it = SequenceRecordReaderDataSetIterator(
            SequenceRecordReader(str(fdir)), batch_size=1,
            num_possible_labels=3, label_index=2,
        )
        ds = it.next()
        assert ds.features.shape == (1, 2, 2)
        np.testing.assert_array_equal(ds.labels[0, 0], [0, 1, 0])
        np.testing.assert_array_equal(ds.labels[0, 1], [0, 0, 1])


class TestTrainingThroughBridge:
    def test_csv_driven_training(self, csv_file):
        """End-to-end: CSV → RecordReaderDataSetIterator → fit (the
        VERDICT done-criterion for this component)."""
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import Adam

        rr = CSVRecordReader(csv_file, skip_num_lines=1)
        it = RecordReaderDataSetIterator(rr, 16, label_index=3,
                                         num_possible_labels=3)
        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.05))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3)).build()
        )
        net = MultiLayerNetwork(conf).init()
        first = None
        for _ in range(15):
            net._fit_one_epoch(it)
            if first is None:
                first = float(net.score_)
        assert float(net.score_) < first


class TestFetchers:
    def test_svhn_shapes(self):
        it = SvhnDataSetIterator(32, num_examples=64)
        ds = it.next()
        assert ds.features.shape == (32, 32, 32, 3)
        assert ds.labels.shape == (32, 10)
        assert 0 <= ds.features.min() and ds.features.max() <= 1

    def test_tiny_imagenet_shapes(self):
        it = TinyImageNetDataSetIterator(16, num_examples=32)
        ds = it.next()
        assert ds.features.shape == (16, 64, 64, 3)
        assert ds.labels.shape == (16, 200)

    def test_uci_sequences_learnable(self):
        """Sequence classes are structurally distinct — a tiny readout on
        summary stats must beat chance (sanity that the generator follows
        the six control-chart processes)."""
        from deeplearning4j_tpu.data.fetchers import load_uci_sequences

        x, y = load_uci_sequences(train=True, num_examples=300)
        assert x.shape == (300, 60, 1)
        assert y.shape == (300, 60, 6)
        cls = y[:, 0].argmax(1)
        # trend classes separable by (end - start); shift classes by
        # half-difference; cyclic by detrended variance
        d_end = x[:, -10:, 0].mean(1) - x[:, :10, 0].mean(1)
        assert d_end[cls == 2].mean() > d_end[cls == 0].mean() + 0.3
        assert d_end[cls == 3].mean() < d_end[cls == 0].mean() - 0.3

    def test_determinism(self):
        a = SvhnDataSetIterator(16, num_examples=16).next()
        b = SvhnDataSetIterator(16, num_examples=16).next()
        np.testing.assert_array_equal(a.features, b.features)


def _write_idx(path, arr):
    """Write a numpy uint8 array in IDX (ubyte) format — the layout
    MnistDbFile.java parses: >I magic (0x08=ubyte, ndim low byte), one >I
    per dim, raw bytes."""
    import struct

    arr = np.asarray(arr, np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


class TestEmnistSplits:
    """All EMNIST splits load from cache-dir IDX files (VERDICT r3 item 9:
    exercise the non-digit path offline with synthetic fixture files;
    reference EmnistDataSetIterator + its Set enum)."""

    def _fixture(self, tmp_path, monkeypatch, stem, n_classes, n=40,
                 one_based=False):
        from deeplearning4j_tpu.data import mnist as mnist_mod

        d = tmp_path / "emnist"
        d.mkdir(exist_ok=True)
        rng = np.random.default_rng(5)
        for split, m in (("train", n), ("test", n // 2)):
            imgs = rng.integers(0, 256, (m, 28, 28), dtype=np.uint8)
            labels = rng.integers(0, n_classes, m).astype(np.uint8)
            if one_based:
                labels = labels + 1
            _write_idx(str(d / f"emnist-{stem}-{split}-images-idx3-ubyte"), imgs)
            _write_idx(str(d / f"emnist-{stem}-{split}-labels-idx1-ubyte"), labels)
        monkeypatch.setattr(mnist_mod, "CACHE_DIR", str(tmp_path))

    @pytest.mark.parametrize("split,stem,ncls", [
        ("balanced", "balanced", 47),
        ("complete", "byclass", 62),
        ("merge", "bymerge", 47),
    ])
    def test_non_digit_split_loads_from_idx(self, tmp_path, monkeypatch,
                                            split, stem, ncls):
        from deeplearning4j_tpu.data.mnist import EmnistDataSetIterator

        self._fixture(tmp_path, monkeypatch, stem, ncls)
        it = EmnistDataSetIterator(16, split=split, train=True)
        assert not it.is_synthetic and it.num_classes == ncls
        ds = it.next()
        assert ds.features.shape == (16, 28, 28, 1)
        assert ds.labels.shape == (16, ncls)
        assert float(ds.labels.sum(1).min()) == 1.0  # valid one-hot rows
        # test split resolves to the smaller file
        it_test = EmnistDataSetIterator(8, split=split, train=False)
        assert it_test._ds.num_examples() == 20

    def test_letters_labels_shift_to_zero_based(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data.mnist import EmnistDataSetIterator

        self._fixture(tmp_path, monkeypatch, "letters", 26, one_based=True)
        it = EmnistDataSetIterator(40, split="letters", shuffle=False)
        ds = it.next()
        assert ds.labels.shape[1] == 26
        assert float(ds.labels.sum(1).min()) == 1.0

    def test_missing_files_raise_with_path(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import mnist as mnist_mod
        from deeplearning4j_tpu.data.mnist import EmnistDataSetIterator

        monkeypatch.setattr(mnist_mod, "CACHE_DIR", str(tmp_path))
        with pytest.raises(FileNotFoundError, match="emnist-balanced"):
            EmnistDataSetIterator(16, split="balanced")
        with pytest.raises(ValueError, match="Unknown EMNIST split"):
            EmnistDataSetIterator(16, split="nonsense")

    def test_digits_split_still_falls_back_synthetic(self, tmp_path,
                                                     monkeypatch):
        from deeplearning4j_tpu.data import mnist as mnist_mod
        from deeplearning4j_tpu.data.mnist import EmnistDataSetIterator

        monkeypatch.setattr(mnist_mod, "CACHE_DIR", str(tmp_path))
        it = EmnistDataSetIterator(8, split="digits", num_examples=16)
        assert it.is_synthetic and it.next().labels.shape == (8, 10)

    def test_parity_helpers(self):
        from deeplearning4j_tpu.data.mnist import EmnistDataSetIterator as E

        assert E.num_labels("letters") == 26 and E.numLabels("COMPLETE") == 62
        assert E.is_balanced("balanced") and not E.isBalanced("byclass")


class TestNativeEtl:
    """Native C++ ETL kernels (native/etl.cpp via ctypes) must agree with
    the numpy fallbacks bit-for-bit on the paths the data bridge uses."""

    def test_available_and_parity(self):
        from deeplearning4j_tpu import native_etl as ne

        rng = np.random.default_rng(1)
        u8 = rng.integers(0, 256, (3, 5, 4, 3)).astype(np.uint8)
        np.testing.assert_allclose(
            ne.u8_to_f32(u8), u8.astype(np.float32) / 255.0, atol=1e-6
        )
        x = rng.standard_normal(64).astype(np.float32)
        np.testing.assert_allclose(
            ne.standardize(x, 0.3, 1.7), (x - 0.3) / 1.7, atol=1e-5
        )
        ids = np.asarray([2, 0, 7, -1], np.int32)
        oh = ne.one_hot(ids, 5)
        assert oh.shape == (4, 5)
        assert oh[2].sum() == 0 and oh[3].sum() == 0  # out of range → zero
        assert oh[0, 2] == 1 and oh[1, 0] == 1
        np.testing.assert_allclose(
            ne.parse_float_line("1,2.5,-3e1"), [1.0, 2.5, -30.0], atol=1e-6
        )

    def test_image_reader_uses_native_scaling(self, tmp_path):
        from PIL import Image

        from deeplearning4j_tpu import native_etl as ne

        d = tmp_path / "c"
        d.mkdir()
        rng = np.random.default_rng(2)
        raw = (rng.random((6, 6, 3)) * 255).astype(np.uint8)
        Image.fromarray(raw).save(d / "img.png")
        rr = ImageRecordReader(6, 6, 3, str(tmp_path))
        arr, label = rr.next_record()
        assert arr.dtype == np.float32
        # exact u8/255 scaling regardless of which path ran
        np.testing.assert_allclose(arr, raw.astype(np.float32) / 255.0,
                                   atol=1e-6)
        if not ne.available():
            pytest.skip("native ETL library not built in this environment")


class TestNativeNlpKernels:
    """C++ skip-gram pair / CBOW window builders (reference
    AggregateSkipGram's native batch-building role) must match the Python
    fallbacks exactly."""

    def _fallback_pairs(self, ids, bs):
        cs, xs = [], []
        n = len(ids)
        for i in range(n):
            b = int(bs[i])
            lo, hi = max(0, i - b), min(n, i + b + 1)
            for j in range(lo, hi):
                if j != i:
                    cs.append(ids[i])
                    xs.append(ids[j])
        return np.asarray(cs, np.int32), np.asarray(xs, np.int32)

    def _require_native(self):
        from deeplearning4j_tpu import native_etl

        lib = native_etl._load()
        if lib is None or getattr(lib, "skipgram_pairs_i32", None) is None:
            pytest.skip("native NLP kernels unavailable (no toolchain)")
        return native_etl

    def test_skipgram_pairs_native_matches_python(self):
        native_etl = self._require_native()

        rng = np.random.default_rng(0)
        for n in (2, 7, 50, 301):
            ids = rng.integers(0, 1000, n).astype(np.int32)
            bs = rng.integers(1, 6, n).astype(np.int32)
            c, x = native_etl.skipgram_pairs(ids, bs)
            c_ref, x_ref = self._fallback_pairs(ids, bs)
            np.testing.assert_array_equal(c, c_ref)
            np.testing.assert_array_equal(x, x_ref)

    def test_cbow_windows_native_matches_python(self):
        native_etl = self._require_native()

        rng = np.random.default_rng(1)
        ids = rng.integers(0, 100, 40).astype(np.int32)
        bs = rng.integers(1, 4, 40).astype(np.int32)
        W = 6
        ctx, m = native_etl.cbow_windows(ids, bs, W)
        ctx_ref = np.zeros((40, W), np.int32)
        m_ref = np.zeros((40, W), np.float32)
        for i in range(40):
            b = int(bs[i])
            js = [j for j in range(max(0, i - b), min(40, i + b + 1))
                  if j != i][:W]
            ctx_ref[i, :len(js)] = ids[js]
            m_ref[i, :len(js)] = 1.0
        np.testing.assert_array_equal(ctx, ctx_ref)
        np.testing.assert_array_equal(m, m_ref)


class TestRecordReaderMultiDataSetIterator:
    def test_multi_reader_multi_slot(self, csv_file, tmp_path):
        """reference RecordReaderMultiDataSetIterator.Builder: two named
        readers in lockstep, column-range inputs, one-hot + regression
        outputs, each in its own MultiDataSet slot."""
        from deeplearning4j_tpu.data.records import (
            RecordReaderMultiDataSetIterator,
        )

        # second reader: a shifted copy of the same 40 rows
        rows = open(csv_file).read().strip().split("\n")[1:]
        p2 = tmp_path / "aux.csv"
        p2.write_text("\n".join(
            ",".join(f"{float(v) + 10:.4f}" for v in r.split(",")[:3])
            for r in rows) + "\n")

        it = (RecordReaderMultiDataSetIterator.builder(16)
              .add_reader("main", CSVRecordReader(csv_file,
                                                  skip_num_lines=1))
              .add_reader("aux", CSVRecordReader(str(p2)))
              .add_input("main", 0, 1)
              .add_input("aux", 0, 2)
              .add_output_one_hot("main", 3, 3)
              .add_output("main", 2, 2)
              .build())
        mds = it.next()
        assert len(mds.features) == 2 and len(mds.labels) == 2
        assert mds.features[0].shape == (16, 2)
        assert mds.features[1].shape == (16, 3)
        assert mds.labels[0].shape == (16, 3)
        assert np.all(mds.labels[0].sum(1) == 1)
        assert mds.labels[1].shape == (16, 1)
        # aux reader really is the +10 shifted main columns
        np.testing.assert_allclose(mds.features[1][:, :2],
                                   mds.features[0] + 10, atol=1e-3)
        total = 16
        while it.has_next():
            total += it.next().features[0].shape[0]
        assert total == 40
        it.reset()
        assert it.has_next()

    def test_builder_validation(self, csv_file):
        from deeplearning4j_tpu.data.records import (
            RecordReaderMultiDataSetIterator,
        )

        b = RecordReaderMultiDataSetIterator.builder(8)
        with pytest.raises(ValueError, match="add_reader"):
            b.build()
        b.add_reader("r", CSVRecordReader(csv_file, skip_num_lines=1))
        b.add_input("nope", 0, 1)
        b.add_output("r", 3, 3)
        with pytest.raises(ValueError, match="unknown reader"):
            b.build()


class TestCifar:
    def test_synthetic_fallback_shapes(self):
        from deeplearning4j_tpu.data.fetchers import CifarDataSetIterator

        it = CifarDataSetIterator(32, train=True, num_examples=64)
        ds = it.next()
        assert ds.features.shape == (32, 32, 32, 3)
        assert ds.labels.shape == (32, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        c100 = CifarDataSetIterator(16, num_examples=16, cifar100=True,
                                    use_coarse_labels=True)
        assert c100.next().labels.shape == (16, 20)

    def test_official_binary_format(self, tmp_path, monkeypatch):
        """Write a real-format cifar-10 binary batch into a fake cache
        dir and read it back through the official-format path."""
        import deeplearning4j_tpu.data.fetchers as F

        monkeypatch.setattr(F, "CACHE_DIR", str(tmp_path))
        d = tmp_path / "cifar" / "cifar-10-batches-bin"
        d.mkdir(parents=True)
        rng = np.random.default_rng(0)
        n = 10
        recs = []
        labels = rng.integers(0, 10, n).astype(np.uint8)
        pixels = rng.integers(0, 256, (n, 3072)).astype(np.uint8)
        for i in range(n):
            recs.append(np.concatenate([[labels[i]], pixels[i]]))
        blob = np.stack(recs).astype(np.uint8).tobytes()
        for i in range(1, 6):
            (d / f"data_batch_{i}.bin").write_bytes(blob)
        x, y = F.load_cifar(train=True)
        assert x.shape == (50, 32, 32, 3) and y.shape == (50, 10)
        np.testing.assert_array_equal(y[:n].argmax(1), labels)
        # CHW -> HWC pixel mapping: channel 0 plane comes first
        np.testing.assert_allclose(
            x[0, 0, 0, 0], pixels[0, 0] / 255.0, atol=1e-6)
        np.testing.assert_allclose(
            x[0, 0, 0, 1], pixels[0, 1024] / 255.0, atol=1e-6)


class TestLfw:
    def test_synthetic_and_real_dir(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import LFWDataSetIterator
        import deeplearning4j_tpu.data.fetchers as F

        it = LFWDataSetIterator(16, num_examples=32)
        ds = it.next()
        assert ds.features.shape == (16, 64, 64, 3)
        assert ds.labels.shape[1] == it.num_labels() == 16

        # real directory layout: person dirs with >= 2 images kept
        from PIL import Image

        monkeypatch.setattr(F, "CACHE_DIR", str(tmp_path))
        base = tmp_path / "lfw" / "lfw"
        for person, n in [("Ada_L", 3), ("Bob_K", 2), ("Solo_X", 1)]:
            d = base / person
            d.mkdir(parents=True)
            for i in range(n):
                Image.new("RGB", (80, 80),
                          (10 * i, 100, 50)).save(d / f"{i}.jpg")
        x, y, people = F.load_lfw(image_size=32)
        assert people == ["Ada_L", "Bob_K"]  # Solo_X filtered (<2 images)
        assert x.shape == (5, 32, 32, 3) and y.shape == (5, 2)
