"""Worker for the 2-process compressed-gradient (SharedTrainingMaster)
test — the reference's core SharedTraining scenario: threshold-encoded
updates crossing HOSTS. Launched by tests/test_multihost.py."""

import os
import sys

coordinator, nprocs, pid, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel.multihost import (  # noqa: E402
    ShardedDataSetIterator,
    initialize,
)
from deeplearning4j_tpu.parallel.mesh import TrainingMesh  # noqa: E402
from deeplearning4j_tpu.parallel.shared_training import (  # noqa: E402
    SharedTrainingMaster,
)
from deeplearning4j_tpu.data.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.data.iterators import ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.updaters import Sgd  # noqa: E402

ctx = initialize(coordinator, num_processes=nprocs, process_id=pid)
assert len(jax.devices()) == 2 * nprocs

rng = np.random.default_rng(777)
centers = rng.standard_normal((3, 5)) * 2
cls = rng.integers(0, 3, 64)
x = (centers[cls] + rng.standard_normal((64, 5)) * 0.3).astype(np.float32)
ds = DataSet(x, np.eye(3, dtype=np.float32)[cls])

conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(1.0))
        .weight_init("xavier").list()
        .layer(DenseLayer(n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(5)).build())
net = MultiLayerNetwork(conf).init()

mesh = TrainingMesh(data=len(jax.devices()))
master = (SharedTrainingMaster.builder(threshold=0.02)
          .update_capacity(512).mesh(mesh).build())
it = ShardedDataSetIterator(ListDataSetIterator(ds, 64), nprocs, pid)
scores = []
for _ in range(40):
    master.fit(net, it, epochs=1)
    scores.append(float(net.score_))

params = net.params_flat()
np.savez(os.path.join(outdir, f"shared_result_{pid}.npz"),
         params=params, first=scores[0], last=scores[-1])
print(f"worker {pid}: {scores[0]:.3f} -> {scores[-1]:.3f}", flush=True)
