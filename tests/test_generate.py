"""Continuous-batching generation tests (serving/generate.py + the
models/transformer_lm.py decode-path rework behind it).

The acceptance spine: a request decoded in the slotted engine among
other requests is BIT-IDENTICAL to the same request decoded alone
(``generate_cached``) and to the full-prefix reference (``generate``);
steady-state decode traces ZERO new XLA programs after warmup; slots
free at token granularity on completion AND mid-decode deadline; the
LSTM carried-state path matches the full-sequence forward. Plus the
satellite contracts: fused on-device sampling parity, bucketed-prefill
retrace guard, the typed context-window error, slab memory validation,
and flight-recorder slot lifecycle events.
"""

import gc
import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer_lm import (
    ContextWindowExceeded,
    TransformerLM,
    _sample_next,
    prefill_bucket_lengths,
    sample_next_device,
)
from deeplearning4j_tpu.serving import (
    GenerationEngine,
    GenerationMemoryError,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
)


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    """Same discipline as test_serving.py: drop this module's compiled
    executables when done (short-lived engines on a cramped CPU host)."""
    yield
    gc.collect()
    jax.clear_caches()


_LM = {}


def _lm() -> TransformerLM:
    """Module-shared tiny LM (one build, one compile set)."""
    if "m" not in _LM:
        m = TransformerLM(vocab_size=48, d_model=32, n_heads=2, n_layers=2,
                          max_length=48, seed=5).init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 48, (4, 24)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        for _ in range(3):
            m.fit_batch(ids, tgt)
        _LM["m"] = m
    return _LM["m"]


def _prompts(n, lens=(3, 21), seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 48, (int(rng.integers(*lens)),)).astype(np.int32)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# in-graph sampler
# ---------------------------------------------------------------------------
class TestDeviceSampler:
    def _logits(self, b=3, V=32, seed=4):
        return np.random.default_rng(seed).standard_normal(
            (b, V)).astype(np.float32)

    def test_greedy_matches_host(self):
        logits = self._logits()
        host, _ = _sample_next(logits, 0.0, 0, 0.0, jax.random.PRNGKey(0))
        dev, _ = sample_next_device(jax.numpy.asarray(logits), 0.0, 0, 0.0,
                                    jax.random.PRNGKey(0))
        np.testing.assert_array_equal(host, np.asarray(dev))

    def test_temperature_top_k_matches_host(self):
        logits = self._logits()
        for temp, k in ((0.7, 0), (1.3, 5), (0.5, 1)):
            host, _ = _sample_next(logits.copy(), temp, k, 0.0,
                                   jax.random.PRNGKey(9))
            dev, _ = sample_next_device(jax.numpy.asarray(logits),
                                        temp, k, 0.0, jax.random.PRNGKey(9))
            np.testing.assert_array_equal(host, np.asarray(dev))

    def test_key_chain_matches_host(self):
        # the advanced key must follow the host's split(rng)[0] chain so
        # fused decoding reproduces generate()'s sampled trajectory
        logits = self._logits()
        _, host_rng = _sample_next(logits, 0.8, 0, 0.0,
                                   jax.random.PRNGKey(3))
        _, dev_key = sample_next_device(jax.numpy.asarray(logits), 0.8, 0,
                                        0.0, jax.random.PRNGKey(3))
        np.testing.assert_array_equal(
            np.asarray(host_rng), np.asarray(dev_key))

    def test_top_p_restricts_support(self):
        # tolerance-documented vs host (cumsum order); assert the
        # in-graph nucleus SEMANTICS: tiny p → argmax support only
        logits = self._logits()
        toks = set()
        for s in range(8):
            dev, _ = sample_next_device(jax.numpy.asarray(logits[:1]), 1.0,
                                        0, 1e-6, jax.random.PRNGKey(s))
            toks.add(int(np.asarray(dev)[0]))
        assert toks == {int(logits[0].argmax())}


# ---------------------------------------------------------------------------
# fused generate_cached (satellites 1-3)
# ---------------------------------------------------------------------------
class TestGenerateCachedFused:
    def test_greedy_parity_across_buckets(self):
        m = _lm()
        for tp in (3, 9, 17, 30):
            prompt = _prompts(1, (tp, tp + 1), seed=tp)[0]
            np.testing.assert_array_equal(
                m.generate(prompt, max_new=6),
                m.generate_cached(prompt, max_new=6))

    def test_prefill_bucketing_bounds_program_count(self):
        # the _jit_cache["prefill"] leak this replaces: one program per
        # DISTINCT prompt length. Now: one per BUCKET.
        m = TransformerLM(vocab_size=32, d_model=32, n_heads=2, n_layers=1,
                          max_length=48, seed=1).init()
        buckets = m.prefill_buckets()
        assert buckets == prefill_bucket_lengths(48, m.serving_seq_buckets)
        for tp in (3, 5, 7, 9, 11, 13):  # all land in the 16 bucket
            m.generate_cached(np.arange(tp, dtype=np.int32), max_new=2)
        assert m.trace_counts.get("prefill") == 1
        assert m.trace_counts.get("decode") == 1
        m.generate_cached(np.arange(20, dtype=np.int32), max_new=2)
        assert m.trace_counts.get("prefill") == 2  # the 32 bucket
        assert m.trace_counts.get("decode") == 1  # decode never re-traces

    def test_context_window_typed_error(self):
        m = _lm()
        with pytest.raises(ContextWindowExceeded, match="max_length") as ei:
            m.generate_cached(np.arange(40, dtype=np.int32), max_new=20)
        assert isinstance(ei.value, ValueError)  # transport maps to 400
        assert ei.value.prompt_len == 40
        assert ei.value.max_new == 20
        assert ei.value.max_length == 48

    def test_window_error_raised_before_sampling_validation(self):
        # the old ordering validated sampling args first, so an
        # overflowing request with bad sampling args reported the wrong
        # failure; the window is the outermost contract
        m = _lm()
        with pytest.raises(ContextWindowExceeded):
            m.generate_cached(np.arange(40, dtype=np.int32), max_new=20,
                              top_k=-3)

    def test_max_new_zero_returns_prompt(self):
        m = _lm()
        prompt = np.arange(5, dtype=np.int32)
        np.testing.assert_array_equal(
            m.generate_cached(prompt, max_new=0), prompt[None])


# ---------------------------------------------------------------------------
# the continuous-batching engine (tentpole)
# ---------------------------------------------------------------------------
_ENG = {}


def _engine() -> GenerationEngine:
    """Module-shared engine over the shared LM, warmed once."""
    if "e" not in _ENG:
        e = GenerationEngine(_lm(), n_slots=3, queue_limit=32,
                             default_timeout_s=120.0)
        e.warmup()
        _ENG["e"] = e
    return _ENG["e"]


class TestGenerationEngine:
    def test_mixed_length_storm_three_way_parity(self):
        # join/leave at token granularity: 8 requests with mixed prompt
        # lengths AND mixed max_new over 3 slots — completions free
        # slots mid-storm and queued requests join between steps. Every
        # output must be bit-identical to solo generate_cached AND to
        # the full-prefix generate reference.
        m, eng = _lm(), _engine()
        rng = np.random.default_rng(7)
        prompts = _prompts(8, (3, 21), seed=7)
        news = [int(rng.integers(3, 12)) for _ in prompts]
        before = dict(eng.trace_counts)
        reqs = [eng.submit(p, max_new=n, timeout=90)
                for p, n in zip(prompts, news)]
        outs = [r.result(timeout=90) for r in reqs]
        assert eng.trace_counts == before  # zero steady-state retraces
        for p, n, out in zip(prompts, news, outs):
            np.testing.assert_array_equal(out, m.generate_cached(
                p, max_new=n)[0])
            np.testing.assert_array_equal(out, m.generate(p, max_new=n)[0])

    def test_sampled_parity_with_solo_by_seed(self):
        m, eng = _lm(), _engine()
        prompt = _prompts(1, seed=3)[0]
        out = eng.submit(prompt, max_new=5, temperature=0.8, top_k=4,
                         seed=13, timeout=90).result(timeout=90)
        solo = m.generate_cached(prompt, max_new=5, temperature=0.8,
                                 top_k=4, rng=jax.random.PRNGKey(13))[0]
        np.testing.assert_array_equal(out, solo)

    def test_streaming_matches_result(self):
        eng = _engine()
        prompt = _prompts(1, seed=5)[0]
        req = eng.submit(prompt, max_new=6, timeout=90)
        streamed = list(req.stream(timeout=90))
        full = req.result(timeout=5)
        assert streamed == full[len(prompt):].tolist()
        assert len(streamed) == 6

    def test_deadline_mid_decode_frees_slot(self):
        eng = _engine()
        prompt = _prompts(1, seed=9)[0]
        max_new = 48 - len(prompt)  # fill the window: a long decode
        req = eng.submit(prompt, max_new=max_new, timeout=0.02)
        with pytest.raises(RequestDeadlineExceeded):
            req.result(timeout=90)
        assert 0 < len(req.tokens) < max_new  # died mid-decode, not queued
        deadline = time.monotonic() + 10
        while eng.active_slots and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.active_slots == 0  # the slot came back
        # and the freed slot serves the next request normally
        out = eng.submit(prompt, max_new=3, timeout=90).result(timeout=90)
        assert out.shape[0] == len(prompt) + 3

    def test_window_overflow_typed_at_submit(self):
        eng = _engine()
        with pytest.raises(ContextWindowExceeded, match="max_length"):
            eng.submit(np.arange(40, dtype=np.int32), max_new=20)

    def test_decode_failure_fails_active_typed_and_engine_survives(self):
        # a decode dispatch blowing up (bad hot-swapped params, device
        # error) must fail the ACTIVE requests typed — not silently
        # kill the worker thread — and the engine must serve the next
        # request normally (slab rebuilt after the donated buffers died
        # with the failed dispatch)
        m = _lm()
        eng = GenerationEngine(m, n_slots=2, queue_limit=8,
                               default_timeout_s=60.0)
        try:
            eng.warmup()
            real = eng.backend.decode
            boom = {"armed": True}

            def exploding(*a, **kw):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected decode failure")
                return real(*a, **kw)

            eng.backend.decode = exploding
            prompt = _prompts(1, seed=41)[0]
            with pytest.raises(RuntimeError, match="injected"):
                eng.submit(prompt, max_new=8, timeout=60).result(timeout=60)
            # worker alive, slot freed, slab rebuilt: next request works
            out = eng.submit(prompt, max_new=4, timeout=60).result(timeout=60)
            np.testing.assert_array_equal(
                out, m.generate_cached(prompt, max_new=4)[0])
        finally:
            eng.shutdown()

    def test_decode_watchdog_fails_hung_dispatch_typed(self):
        # a decode dispatch that HANGS (vs one that raises — the case
        # above) wedges the worker thread where the except-clause can
        # never run; the watchdog must fail the active requests typed
        # and record the escalated stall, instead of every caller
        # hanging with the worker
        from deeplearning4j_tpu.obs import flight
        from deeplearning4j_tpu.serving import DecodeStalledError

        m = _lm()
        eng = GenerationEngine(m, n_slots=2, queue_limit=8,
                               default_timeout_s=60.0,
                               watchdog_mult=2.0, watchdog_min_s=0.3)
        try:
            eng.warmup()
            real = eng.backend.decode
            hang = {"armed": True}

            def hung(*a, **kw):
                if hang["armed"]:
                    hang["armed"] = False
                    time.sleep(1.5)  # well past the watchdog limit
                return real(*a, **kw)

            eng.backend.decode = hung
            prompt = _prompts(1, seed=51)[0]
            t0 = time.monotonic()
            with pytest.raises(DecodeStalledError, match="stuck"):
                eng.submit(prompt, max_new=8, timeout=60).result(timeout=60)
            # the caller unblocked while the dispatch was still hung
            assert time.monotonic() - t0 < 1.4
            evs = flight.default_flight_recorder().events()
            assert any(e["kind"] == "decode_stall" and e.get("escalated")
                       for e in evs)
            # engine recovers once the hung dispatch returns: slab
            # rebuilt, next request decodes normally
            out = eng.submit(prompt, max_new=4, timeout=60).result(
                timeout=60)
            np.testing.assert_array_equal(
                out, m.generate_cached(prompt, max_new=4)[0])
        finally:
            eng.shutdown()

    def test_overload_typed(self):
        # 1-slot engine with a 1-deep queue: the third concurrent
        # request must reject typed, not block
        m = _lm()
        eng = GenerationEngine(m, n_slots=1, queue_limit=1,
                               default_timeout_s=60.0)
        try:
            held = []
            for i in range(2):
                held.append(eng.submit(_prompts(1, seed=i)[0], max_new=30,
                                       timeout=60))
                # let the worker drain the queue into the slot before the
                # next submit (admission capacity = slots + queue depth,
                # but only after the pop — don't race it)
                t_end = time.monotonic() + 10
                while (i == 0 and eng.queue_depth()
                       and time.monotonic() < t_end):
                    time.sleep(0.005)
            with pytest.raises(ServerOverloadedError):
                for i in range(20):  # at most 1 admits before the check
                    eng.submit(_prompts(1, seed=90 + i)[0], max_new=30,
                               timeout=60)
            for r in held:
                r.result(timeout=60)
        finally:
            eng.shutdown()

    def test_memory_limit_typed_at_build(self):
        with pytest.raises(GenerationMemoryError, match="n_slots"):
            GenerationEngine(_lm(), n_slots=2, memory_limit_bytes=1)

    def test_memory_report_shape(self):
        rep = _engine().memory_report
        assert rep["cache_bytes"] > 0
        assert rep["param_bytes"] > 0
        assert rep["total_bytes"] == rep["cache_bytes"] + rep["param_bytes"]

    def test_flight_events_slot_lifecycle(self):
        from deeplearning4j_tpu.obs.flight import default_flight_recorder

        rec = default_flight_recorder()
        mark = rec.recorded_total
        eng = _engine()
        eng.submit(_prompts(1, seed=21)[0], max_new=3,
                   timeout=90).result(timeout=90)
        # recorded_total is the NEXT seq to assign: new events are >= it
        new = [e for e in rec.events() if e.get("seq", 0) >= mark]
        kinds = {e["kind"] for e in new}
        assert "slot_claim" in kinds
        assert "slot_free" in kinds
        claim = next(e for e in new if e["kind"] == "slot_claim")
        assert claim["prompt_len"] > 0 and claim["prompt_bucket"] > 0
        free = next(e for e in new if e["kind"] == "slot_free")
        assert free["reason"] == "done" and free["tokens"] == 3

    def test_rtrace_timeline_stages(self):
        eng = _engine()
        req = eng.submit(_prompts(1, seed=23)[0], max_new=3, timeout=90,
                         trace=True)
        req.result(timeout=90)
        tl = req.trace.timeline()
        stages = [s["stage"] for s in tl["stages"]]
        assert stages == ["queue", "prefill", "decode", "respond"]
        assert tl["tokens"] == 3
        assert tl["slot"] is not None
        assert tl["total_ms"] == pytest.approx(
            sum(s["ms"] for s in tl["stages"]), abs=0.1)

    def test_shutdown_drains_then_rejects(self):
        eng = GenerationEngine(_lm(), n_slots=2, queue_limit=8,
                               default_timeout_s=60.0)
        reqs = [eng.submit(_prompts(1, seed=31 + i)[0], max_new=4,
                           timeout=60) for i in range(4)]
        eng.shutdown(drain=True)
        for r in reqs:
            assert r.result(timeout=10).shape[0] > 0  # drained, served
        with pytest.raises(ServerShutdownError):
            eng.submit(_prompts(1, seed=40)[0], max_new=2)

    def test_describe(self):
        d = _engine().describe()
        assert d["backend"] == "transformer"
        assert d["n_slots"] == 3
        assert d["prefill_buckets"][-1] == 48
        assert "generation_decode" in d["trace_counts"]


# ---------------------------------------------------------------------------
# speculative decoding + shared-prefix KV reuse
# ---------------------------------------------------------------------------
_SPEC = {}


def _spec_engine() -> GenerationEngine:
    """Module-shared speculating engine (K=4 proposal lane + prefix
    cache) over the shared LM, warmed once."""
    if "e" not in _SPEC:
        e = GenerationEngine(_lm(), n_slots=3, queue_limit=32,
                             default_timeout_s=120.0, spec_decode_k=4,
                             prefix_cache_mb=2.0)
        e.warmup()
        _SPEC["e"] = e
    return _SPEC["e"]


class TestSpeculativePrefix:
    def test_four_way_greedy_parity_zero_retrace(self):
        # the fourth parity leg: the SPECULATING engine — drafts
        # proposed and sometimes rejected, prefix hits replacing
        # prefills on the repeat round — must stay bit-identical to the
        # plain engine, to solo generate_cached, and to the full-prefix
        # reference, and trace NOTHING after warmup (verify dispatches
        # and prefix-hit restores included)
        m, plain, spec = _lm(), _engine(), _spec_engine()
        prompts = _prompts(6, (3, 21), seed=16)
        news = [9, 5, 12, 7, 4, 10]
        before = dict(spec.trace_counts)
        reqs = [spec.submit(p, max_new=n, timeout=90)
                for p, n in zip(prompts, news)]
        outs = [r.result(timeout=90) for r in reqs]
        # resubmit the same prompts: every admission is now a prefix HIT
        reqs2 = [spec.submit(p, max_new=n, timeout=90)
                 for p, n in zip(prompts, news)]
        outs2 = [r.result(timeout=90) for r in reqs2]
        assert spec.trace_counts == before  # zero retraces, spec on
        assert spec.describe()["prefix_cache"]["hits"] >= len(prompts)
        for p, n, out, out2 in zip(prompts, news, outs, outs2):
            np.testing.assert_array_equal(out, out2)
            np.testing.assert_array_equal(
                out,
                plain.submit(p, max_new=n, timeout=90).result(timeout=90))
            np.testing.assert_array_equal(
                out, m.generate_cached(p, max_new=n)[0])
            np.testing.assert_array_equal(out, m.generate(p, max_new=n)[0])

    def test_sampled_key_chain_parity_with_rejection(self):
        # sampled path: rejected drafts must not desync the per-slot
        # PRNG chain — the key advances once per EMITTED token, so a
        # seeded spec request reproduces the solo trajectory exactly
        m, spec = _lm(), _spec_engine()
        prompt = _prompts(1, seed=17)[0]
        req = spec.submit(prompt, max_new=8, temperature=0.9, top_k=6,
                          seed=23, timeout=90)
        out = req.result(timeout=90)
        solo = m.generate_cached(prompt, max_new=8, temperature=0.9,
                                 top_k=6, rng=jax.random.PRNGKey(23))[0]
        np.testing.assert_array_equal(out, solo)

    def test_completion_replay_high_acceptance_on_repeat(self):
        # a prefix hit replays the prompt's recorded first greedy
        # completion as its draft source: near-total acceptance on the
        # repeat, far beyond what the n-gram table manages cold
        eng = _spec_engine()
        prompt = _prompts(1, (10, 11), seed=77)[0]
        first = eng.generate(prompt, max_new=16, timeout=90)
        req = eng.submit(prompt, max_new=16, timeout=90)
        np.testing.assert_array_equal(first, req.result(timeout=90))
        assert req.draft_proposed > 0
        assert req.draft_accepted >= 0.8 * req.draft_proposed

    def test_prefix_hit_miss_evict_lifecycle(self):
        from deeplearning4j_tpu.obs.flight import default_flight_recorder

        m = _lm()
        # budget fits exactly ONE bucket-32 KV block: the second
        # distinct prompt LRU-evicts the first, re-requesting the first
        # is a miss again
        eng = GenerationEngine(m, n_slots=2, queue_limit=8,
                               default_timeout_s=60.0,
                               prefix_cache_mb=0.02)
        try:
            eng.warmup()
            rec = default_flight_recorder()
            mark = rec.recorded_total
            p1 = _prompts(1, (20, 21), seed=61)[0]
            p2 = _prompts(1, (20, 21), seed=62)[0]
            a1 = eng.generate(p1, max_new=4, timeout=60)  # miss: captured
            b1 = eng.generate(p1, max_new=4, timeout=60)  # hit
            np.testing.assert_array_equal(a1, b1)
            eng.generate(p2, max_new=4, timeout=60)  # miss: evicts p1
            eng.generate(p1, max_new=4, timeout=60)  # miss again
            d = eng.describe()["prefix_cache"]
            assert (d["lookups"], d["hits"], d["entries"]) == (4, 1, 1)
            assert 0 < d["bytes"] <= d["limit_bytes"]
            new = [e for e in rec.events() if e.get("seq", 0) >= mark]
            assert any(e["kind"] == "prefix_hit" for e in new)
            assert any(e["kind"] == "prefix_evict"
                       and e["reason"] == "lru" for e in new)
            claims = [e for e in new if e["kind"] == "slot_claim"]
            assert [c["prefix_hit"] for c in claims] == [
                False, True, False, False]
        finally:
            eng.shutdown()

    def test_deadline_mid_verify_frees_slot(self):
        # deadline expiry lands between verify dispatches exactly like
        # between plain decode steps: already-accepted tokens kept,
        # slot freed at token granularity, engine serves the next
        # request normally
        eng = _spec_engine()
        prompt = _prompts(1, seed=19)[0]
        max_new = 48 - len(prompt)
        req = eng.submit(prompt, max_new=max_new, timeout=0.02)
        with pytest.raises(RequestDeadlineExceeded):
            req.result(timeout=90)
        assert 0 < len(req.tokens) < max_new  # died mid-decode
        deadline = time.monotonic() + 10
        while eng.active_slots and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.active_slots == 0
        out = eng.submit(prompt, max_new=3, timeout=90).result(timeout=90)
        assert out.shape[0] == len(prompt) + 3


# ---------------------------------------------------------------------------
# LSTM carried-state backend
# ---------------------------------------------------------------------------
class TestRecurrentGeneration:
    @pytest.fixture(scope="class")
    def net(self):
        from deeplearning4j_tpu.models.textgen_lstm import TextGenerationLSTM

        return TextGenerationLSTM(num_classes=12, units=16).init()

    def _host_greedy(self, net, prompt, max_new):
        """Reference: re-run the FULL sequence forward per token."""
        seq = list(int(t) for t in prompt)
        for _ in range(max_new):
            x = np.zeros((1, len(seq), 12), np.float32)
            x[0, np.arange(len(seq)), seq] = 1.0
            y = net.output(x)
            seq.append(int(y[0, -1].argmax()))
        return np.asarray(seq, np.int32)

    def test_carried_state_parity_vs_full_forward(self, net):
        eng = GenerationEngine(net, n_slots=2, max_length=64,
                               queue_limit=16, default_timeout_s=90.0)
        try:
            eng.warmup()
            before = dict(eng.trace_counts)
            rng = np.random.default_rng(2)
            cases = []
            for i in range(4):
                tp = int(rng.integers(3, 14))
                prompt = rng.integers(0, 12, (tp,)).astype(np.int32)
                mn = int(rng.integers(3, 8))
                cases.append((prompt, mn,
                              eng.submit(prompt, max_new=mn, timeout=90)))
            for prompt, mn, req in cases:
                np.testing.assert_array_equal(
                    req.result(timeout=90),
                    self._host_greedy(net, prompt, mn))
            assert eng.trace_counts == before  # recurrent path: 0 too
            assert eng.backend.kind == "recurrent"
        finally:
            eng.shutdown()

    def test_unsupported_model_typed(self):
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(TypeError, match="incremental-decode"):
            GenerationEngine(net, n_slots=1)


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------
def _http(port, method, path, body=None, timeout=90):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 None if body is None else json.dumps(body))
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp, raw


class TestGenerateHTTP:
    @pytest.fixture(scope="class")
    def served(self):
        from deeplearning4j_tpu.serving import (
            BucketPolicy,
            InferenceEngine,
            InferenceServer,
        )

        m = _lm()
        gen = _engine()
        eng = InferenceEngine(m, buckets=BucketPolicy(batch_buckets=[1]))
        srv = InferenceServer(eng, port=0, generation=gen).start()
        yield srv, m
        # detach the shared engine before server shutdown would drain it
        srv.generation = None
        srv.shutdown()

    def test_generate_non_stream_parity(self, served):
        srv, m = served
        resp, raw = _http(srv.port, "POST", "/generate",
                          {"prompt": [1, 2, 3], "max_new": 5,
                           "stream": False})
        assert resp.status == 200
        body = json.loads(raw)
        solo = m.generate_cached(np.asarray([1, 2, 3], np.int32),
                                 max_new=5)[0]
        assert body["sequence"] == solo.tolist()
        assert body["tokens"] == solo[3:].tolist()

    def test_generate_stream_chunks(self, served):
        srv, m = served
        resp, raw = _http(srv.port, "POST", "/generate",
                          {"prompt": [4, 5, 6, 7], "max_new": 4})
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "application/x-ndjson"
        lines = [json.loads(ln) for ln in
                 raw.decode().strip().split("\n")]
        toks = [ln["token"] for ln in lines[:-1]]
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == toks
        solo = m.generate_cached(np.asarray([4, 5, 6, 7], np.int32),
                                 max_new=4)[0]
        assert toks == solo[4:].tolist()

    def test_generate_window_overflow_400(self, served):
        srv, _ = served
        resp, raw = _http(srv.port, "POST", "/generate",
                          {"prompt": list(range(40)), "max_new": 20,
                           "stream": False})
        assert resp.status == 400
        assert json.loads(raw)["error"] == "ContextWindowExceeded"

    def test_generate_bad_payload_400(self, served):
        srv, _ = served
        resp, raw = _http(srv.port, "POST", "/generate", {"max_new": 3})
        assert resp.status == 400

    def test_healthz_and_metrics_expose_generation(self, served):
        srv, _ = served
        resp, raw = _http(srv.port, "GET", "/healthz")
        info = json.loads(raw)["generation"]
        assert info["backend"] == "transformer"
        resp, raw = _http(srv.port, "GET", "/metrics")
        gen = json.loads(raw)["generation"]
        assert gen["tokens"] > 0
        assert gen["slots"] == 3

    def test_generate_409_without_engine(self):
        from deeplearning4j_tpu.serving import (
            BucketPolicy,
            InferenceEngine,
            InferenceServer,
        )

        eng = InferenceEngine(_lm(), buckets=BucketPolicy(batch_buckets=[1]))
        srv = InferenceServer(eng, port=0).start()
        try:
            resp, raw = _http(srv.port, "POST", "/generate",
                              {"prompt": [1], "max_new": 2})
            assert resp.status == 409
            assert json.loads(raw)["error"] == "NoGenerationEngine"
        finally:
            srv.shutdown()


def teardown_module(module):
    eng = _ENG.pop("e", None)
    if eng is not None:
        eng.shutdown()
    _LM.clear()
