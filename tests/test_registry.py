"""Continuous train→serve deployment tests (serving/registry.py +
RegistryPublishListener + the multi-model HTTP routes).

The acceptance spine (ISSUE 11): a NaN-poisoned and a score-regressed
snapshot published from a live fit are refused or auto-rolled back;
serving never returns a result from the bad version after
``regression_trip``; in-flight old-version requests all complete; and
``cli flight-dump`` renders the ordered ``publish → canary_start →
regression_trip → rollback`` timeline. Plus the store's crash-resume
drill (SIGKILL between journal append and registry.json replace —
mirror of the tune/store.py torn-line semantics), per-tenant quota
isolation, LRU eviction/rewarm, the corrupt-snapshot publish fallback,
and Retry-After on both 503 surfaces.
"""

import gc
import http.client
import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import flight
from deeplearning4j_tpu.serving import (
    CanaryRolledBackError,
    InferenceServer,
    ModelRegistry,
    ModelRouter,
    RegistryError,
    ServerOverloadedError,
    SnapshotValidationError,
    TenantQuotaExceededError,
)
from deeplearning4j_tpu.train.earlystopping import DataSetLossCalculator
from deeplearning4j_tpu.train.faults import save_checkpoint, truncate_file
from deeplearning4j_tpu.train.listeners import RegistryPublishListener


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    """Same discipline as test_serving.py: this module builds many
    short-lived engines; drop their executables when done."""
    yield
    gc.collect()
    jax.clear_caches()


N_IN, N_OUT = 4, 3


def _net(seed: int = 7, hidden: int = 8) -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(seed)
        .list()
        .layer(DenseLayer(n_out=hidden, activation="relu"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                           loss="mcxent"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rows(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).standard_normal(
        (n, N_IN)).astype(np.float32)


def _batches(n_batches: int = 4, bs: int = 16, seed: int = 3):
    """Learnable synthetic task: labels from a fixed linear rule."""
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((N_IN, N_OUT))
    out = []
    for _ in range(n_batches):
        x = rng.standard_normal((bs, N_IN)).astype(np.float32)
        y = np.eye(N_OUT, dtype=np.float32)[np.argmax(x @ w, axis=1)]
        out.append(DataSet(x, y))
    return out


def _publish_first(reg, name, seed=1, score=0.5, hidden=8, tmp=None):
    path = save_checkpoint(_net(seed, hidden), str(tmp / f"ck_{name}"))
    return reg.publish(name, path, score=score)


def _flight_kinds(since_seq=0, kinds=None):
    evs = flight.default_flight_recorder().events()
    out = [(e["seq"], e["kind"], e) for e in evs if e["seq"] >= since_seq]
    if kinds is not None:
        out = [t for t in out if t[1] in kinds]
    return out


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------
class TestRegistryStore:
    def test_publish_auto_activates_first_version(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        rec = _publish_first(reg, "m", tmp=tmp_path)
        assert rec["version"] == 1 and rec["status"] == "active"
        assert reg.resolve("m")["version"] == 1
        # the registry owns its copy: deleting the trainer's checkpoint
        # does not unpublish the version
        assert rec["path"].startswith(str(tmp_path / "reg"))
        assert os.path.exists(rec["path"])

    def test_nan_score_refused_typed_and_journaled(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", tmp=tmp_path)
        seq0 = flight.default_flight_recorder().recorded_total
        path = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        with pytest.raises(SnapshotValidationError, match="non-finite"):
            reg.publish("m", path, score=float("nan"))
        st = reg.get("m")
        assert st["active_version"] == 1  # untouched
        assert st["versions"]["2"]["status"] == "rejected"
        kinds = [k for _, k, _ in _flight_kinds(seq0)]
        assert "publish_refused" in kinds

    def test_regressed_score_refused_and_tolerance(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"),
                            regression_tolerance=0.10)
        _publish_first(reg, "m", score=1.0, tmp=tmp_path)
        path = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        # within tolerance: accepted
        rec = reg.publish("m", path, score=1.05)
        assert rec["status"] == "validated"
        # beyond tolerance vs BEST validated (1.0): refused
        with pytest.raises(SnapshotValidationError, match="regressed"):
            reg.publish("m", path, score=1.2)

    def test_higher_is_better_direction(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"), higher_is_better=True)
        _publish_first(reg, "m", score=0.8, tmp=tmp_path)
        path = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        with pytest.raises(SnapshotValidationError, match="regressed"):
            reg.publish("m", path, score=0.5)
        assert reg.publish("m", path, score=0.9)["status"] == "validated"

    def test_unscored_publish_refused_unless_opted_in(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        path = save_checkpoint(_net(1), str(tmp_path / "ck"))
        with pytest.raises(SnapshotValidationError, match="no validation"):
            reg.publish("m", path)
        rec = reg.publish("m", path, allow_unvalidated=True)
        assert rec["status"] == "active"  # first version bootstraps

    def test_rejected_version_cannot_activate(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", tmp=tmp_path)
        path = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        with pytest.raises(SnapshotValidationError):
            reg.publish("m", path, score=float("inf"))
        with pytest.raises(SnapshotValidationError):
            reg.activate("m", 2)

    def test_corrupt_newest_snapshot_publish_falls_back(self, tmp_path):
        # the regression the ISSUE names: a snapshot TRUNCATED
        # mid-publish (crash between the trainer's write and the
        # publish) resolves to the newest valid sibling, with a
        # checkpoint_fallback flight event naming the SKIPPED path and
        # its error class
        ckdir = tmp_path / "ck"
        save_checkpoint(_net(1), str(ckdir), stem="older")
        time.sleep(0.02)  # distinct mtimes: newest must be the truncated
        newest = save_checkpoint(_net(2), str(ckdir), stem="newer")
        truncate_file(newest, 0.4)
        seq0 = flight.default_flight_recorder().recorded_total
        reg = ModelRegistry(str(tmp_path / "reg"))
        rec = reg.publish("m", str(ckdir), score=0.5)
        assert rec["source"].endswith("older.zip")
        evs = [e for _, k, e in _flight_kinds(seq0, {"checkpoint_fallback"})]
        assert evs, "no checkpoint_fallback flight event"
        assert any(e.get("skipped", "").endswith("newer.zip")
                   and e.get("error_class") in ("unreadable_zip",
                                                "crc_mismatch",
                                                "missing_entries")
                   for e in evs)

    def test_keep_last_prunes_disposable_not_active(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"), keep_last=1,
                            regression_tolerance=10.0)
        _publish_first(reg, "m", score=1.0, tmp=tmp_path)
        paths = [reg.get("m")["versions"]["1"]["path"]]
        for i in range(2, 5):
            p = save_checkpoint(_net(i), str(tmp_path / f"ck{i}"))
            rec = reg.publish("m", p, score=1.0)
            paths.append(rec["path"])
        st = reg.get("m")
        assert st["active_version"] == 1
        assert os.path.exists(paths[0])  # active never pruned
        assert os.path.exists(paths[-1])  # newest validated kept
        # middle disposables pruned beyond keep_last
        assert not os.path.exists(paths[1])


# ---------------------------------------------------------------------------
# crash resume
# ---------------------------------------------------------------------------
class TestRegistryCrashResume:
    def test_torn_trailing_journal_line_dropped(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", tmp=tmp_path)
        with open(reg.journal_path, "a") as f:
            f.write('{"kind": "activate", "na')  # SIGKILL mid-append
        with pytest.warns(UserWarning, match="torn trailing"):
            reg2 = ModelRegistry(str(tmp_path / "reg"))
        assert reg2.resolve("m")["version"] == 1

    def test_torn_middle_line_refuses(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", tmp=tmp_path)
        lines = open(reg.journal_path).read().splitlines()
        lines[0] = lines[0][: len(lines[0]) // 2]
        with open(reg.journal_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        with pytest.raises(RegistryError, match="refusing to replay"):
            ModelRegistry(str(tmp_path / "reg"))

    def test_sigkill_between_journal_append_and_snapshot(self, tmp_path):
        # the ISSUE's drill: the journal has the validated/activate
        # records but registry.json is STALE (the crash landed between
        # the fsync'd append and the atomic snapshot replace). Restart
        # must replay the journal and resolve to the last VALIDATED
        # version, ignoring the stale snapshot.
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", score=1.0, tmp=tmp_path)
        stale = open(reg.snapshot_path).read()
        p2 = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        reg.publish("m", p2, score=0.5)
        reg.activate("m", 2)
        # simulate the crash: restore the PRE-publish registry.json; the
        # journal keeps the newer records
        with open(reg.snapshot_path, "w") as f:
            f.write(stale)
        reg2 = ModelRegistry(str(tmp_path / "reg"))
        assert reg2.resolve("m")["version"] == 2
        assert reg2.get("m")["versions"]["2"]["validation"]["ok"]

    def test_refresh_sees_foreign_appends_after_own_append(self, tmp_path):
        # two registry handles over one directory (trainer + server
        # processes): B's OWN append lands after A's un-folded lines,
        # and must not absorb them into its folded-bytes tracking — or
        # refresh() would skip A's publish forever and the new version
        # would never be adopted
        reg_a = ModelRegistry(str(tmp_path / "reg"))
        reg_b = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg_a, "m", score=1.0, tmp=tmp_path)
        reg_b.define_model("other")  # B appends without refreshing first
        assert reg_b.refresh() is True
        assert reg_b.resolve("m")["version"] == 1
        # and A picks up B's model on ITS next refresh
        assert reg_a.refresh() is True
        assert "other" in reg_a.models()

    def test_refused_publish_keeps_no_snapshot_bytes(self, tmp_path):
        # a rejected snapshot can never activate; its copied zip must
        # not accumulate (one refused multi-GB snapshot per checkpoint
        # cadence would fill the disk)
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", score=1.0, tmp=tmp_path)
        p = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        with pytest.raises(SnapshotValidationError):
            reg.publish("m", p, score=9.9)
        snaps = os.listdir(os.path.join(str(tmp_path / "reg"),
                                        "snapshots", "m"))
        assert snaps == ["v0001.zip"]

    def test_canary_mid_window_restarts_cleanly(self, tmp_path):
        # a canary that was mid-window when the process died: the
        # journal holds canary_start with no promote/rollback after it —
        # a fresh router resumes the canary (fresh counters, window
        # restarts) instead of forgetting or half-promoting it
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "m", score=1.0, tmp=tmp_path)
        p2 = save_checkpoint(_net(2), str(tmp_path / "ck2"))
        reg.publish("m", p2, score=0.9)
        reg.start_canary("m", 2, fraction=0.5, window_s=30.0)
        assert reg.canary_state("m")["version"] == 2
        # "restart": fresh registry + fresh router over the same dir
        reg2 = ModelRegistry(str(tmp_path / "reg"))
        assert reg2.canary_state("m")["version"] == 2
        router = ModelRouter(reg2, batch_limit=8, max_wait_ms=1.0,
                             canary_fraction=1.0, canary_window_s=30.0)
        try:
            mm = router.managed("m")
            assert mm.canary is not None and mm.canary.version == 2
            assert mm.canary.stats.requests == 0  # fresh window
            out, v = router.predict("m", _rows(2), timeout=30)
            assert v == 2  # fraction 1.0 → routed to the resumed canary
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------
class TestRouter:
    def _registry_two_models(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "alpha", seed=1, hidden=8, tmp=tmp_path)
        _publish_first(reg, "beta", seed=2, hidden=16, tmp=tmp_path)
        return reg

    def test_routes_two_models_bit_exact(self, tmp_path):
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0)
        try:
            x = _rows(3)
            out_a, va = router.predict("alpha", x, timeout=30)
            out_b, vb = router.predict("beta", x, timeout=30)
            assert va == 1 and vb == 1
            # bit-exact vs each model's own engine forward
            ref_a = router.managed("alpha").active.engine.infer(x)
            ref_b = router.managed("beta").active.engine.infer(x)
            np.testing.assert_array_equal(out_a, ref_a)
            np.testing.assert_array_equal(out_b, ref_b)
            assert out_a.shape == out_b.shape  # same head, different nets
            assert not np.array_equal(out_a, out_b)
        finally:
            router.shutdown()

    def test_unknown_model_typed(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        router = ModelRouter(reg)
        try:
            from deeplearning4j_tpu.serving import UnknownModelError

            with pytest.raises(UnknownModelError):
                router.predict("ghost", _rows(1), timeout=5)
        finally:
            router.shutdown()

    def test_canary_promotes_after_clean_window(self, tmp_path):
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             canary_fraction=0.5, canary_window_s=0.3,
                             canary_min_requests=2, refresh_s=0.01)
        try:
            x = _rows(2)
            router.predict("alpha", x, timeout=30)
            p2 = save_checkpoint(_net(11), str(tmp_path / "ck_a2"))
            rec = reg.publish("alpha", p2, score=0.4)
            v2 = rec["version"]
            seen = set()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                _, v = router.predict("alpha", x, timeout=30)
                seen.add(v)
                if reg.get("alpha")["active_version"] == v2:
                    break
                time.sleep(0.01)
            assert reg.get("alpha")["active_version"] == v2
            assert seen == {1, v2}  # both versions served during canary
            # post-promote traffic serves the new version only
            _, v = router.predict("alpha", x, timeout=30)
            assert v == v2
        finally:
            router.shutdown()

    def test_dispatch_failure_trips_rollback(self, tmp_path):
        # any canary dispatch failure must trip regression_trip →
        # rollback; the active version's in-flight requests complete and
        # no bad-version result reaches a caller after the trip
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             canary_fraction=0.5, canary_window_s=30.0,
                             refresh_s=0.01)
        try:
            x = _rows(2)
            router.predict("alpha", x, timeout=30)
            p2 = save_checkpoint(_net(12), str(tmp_path / "ck_a2"))
            rec = reg.publish("alpha", p2, score=0.4)
            v2 = rec["version"]
            mm = router.managed("alpha")
            # adopt the canary on the next submit, then poison it
            seq0 = flight.default_flight_recorder().recorded_total
            deadline = time.monotonic() + 20
            while mm.canary is None and time.monotonic() < deadline:
                router.predict("alpha", x, timeout=30)
            assert mm.canary is not None

            def exploding(x, mask=None):
                raise RuntimeError("injected canary dispatch failure")

            mm.canary.engine.infer_versioned = exploding
            results = []
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    _, v = router.predict("alpha", x, timeout=30)
                    results.append(v)
                except (RuntimeError, CanaryRolledBackError):
                    pass
                if reg.get("alpha")["versions"][str(v2)]["status"] \
                        == "rolled_back":
                    break
            st = reg.get("alpha")
            assert st["versions"][str(v2)]["status"] == "rolled_back"
            assert st["active_version"] == 1
            # ordered trip → rollback in the flight ring
            kinds = [k for _, k, _ in _flight_kinds(
                seq0, {"regression_trip", "rollback"})]
            assert kinds[:2] == ["regression_trip", "rollback"]
            # nothing served by the bad version, before or after
            assert v2 not in set(results)
            # active version still serves after the rollback
            _, v = router.predict("alpha", x, timeout=30)
            assert v == 1
        finally:
            router.shutdown()

    def test_tenant_quota_typed_others_unaffected(self, tmp_path):
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             tenant_quota=3)
        try:
            x = _rows(1)
            mm = router.managed("alpha")
            orig = mm.active.engine.infer_versioned

            def slow(x, mask=None):
                time.sleep(0.15)
                return orig(x, mask)

            mm.active.engine.infer_versioned = slow
            held, rejects = [], 0
            last = None
            for _ in range(10):
                try:
                    held.append(router.submit("alpha", x, timeout=30,
                                              tenant="noisy"))
                except TenantQuotaExceededError as e:
                    rejects += 1
                    last = e
            assert rejects > 0
            assert last.tenant == "noisy"
            assert isinstance(last, ServerOverloadedError)  # 503 family
            assert last.retry_after_s >= 1.0
            # the quiet tenant is admitted while noisy is rejected
            out, _ = router.predict("alpha", x, timeout=30, tenant="quiet")
            assert out.shape == (1, N_OUT)
            for r in held:
                r.result(timeout=30)
            mm.active.engine.infer_versioned = orig
        finally:
            router.shutdown()

    def test_lru_evict_and_rewarm_flight_events(self, tmp_path):
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             max_live_models=1)
        try:
            seq0 = flight.default_flight_recorder().recorded_total
            router.predict("alpha", _rows(1), timeout=30)
            router.predict("beta", _rows(1), timeout=30)  # evicts alpha
            router.predict("alpha", _rows(1), timeout=30)  # rewarm alpha
            evs = _flight_kinds(seq0, {"model_evict", "model_rewarm"})
            kinds = [(k, e["model"]) for _, k, e in evs]
            assert ("model_rewarm", "alpha") == kinds[0]
            assert ("model_evict", "alpha") in kinds
            assert ("model_rewarm", "beta") in kinds
            # alpha rewarmed again after eviction
            assert kinds.count(("model_rewarm", "alpha")) == 2
        finally:
            router.shutdown()

    def test_multiplexed_storm_zero_steady_state_retraces(self, tmp_path):
        # the ISSUE's multiplexed drill: 2 models + 1 canary version
        # under a mixed storm, per-tenant quotas armed, and ZERO
        # steady-state retraces across every live engine
        reg = self._registry_two_models(tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             queue_limit=4096, tenant_quota=64,
                             canary_fraction=0.25, canary_window_s=60.0,
                             refresh_s=0.01)
        try:
            # warm both models and the canary BEFORE counting
            router.predict("alpha", _rows(1), timeout=30)
            router.predict("beta", _rows(1), timeout=30)
            p2 = save_checkpoint(_net(13), str(tmp_path / "ck_a2"))
            reg.publish("alpha", p2, score=0.4)
            deadline = time.monotonic() + 20
            while (router.managed("alpha").canary is None
                   and time.monotonic() < deadline):
                router.predict("alpha", _rows(1), timeout=30)
            assert router.managed("alpha").canary is not None

            def retraces():
                fam = router.metrics.registry.family_values(
                    "jit_retraces_total")
                return sum(fam.values())

            before = retraces()
            names = ["alpha", "beta"]
            errs = []

            def client(tid):
                rng = np.random.default_rng(tid)
                for i in range(12):
                    n = int(rng.integers(1, 9))
                    try:
                        router.predict(names[(tid + i) % 2], _rows(n, seed=i),
                                       timeout=30, tenant=f"t{tid}")
                    except (TenantQuotaExceededError,
                            CanaryRolledBackError):
                        pass  # quota sheds are part of the drill
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            assert retraces() - before == 0
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------
def _http(port, method, path, body=None, headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 None if body is None else json.dumps(body),
                 headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    hdrs = dict(resp.getheaders())
    conn.close()
    return resp.status, (json.loads(data) if data else {}), hdrs


class TestRegistryHTTP:
    @pytest.fixture()
    def served(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        _publish_first(reg, "alpha", seed=1, tmp=tmp_path)
        _publish_first(reg, "beta", seed=2, hidden=16, tmp=tmp_path)
        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             tenant_quota=3)
        server = InferenceServer(router=router, port=0).start()
        try:
            yield server, router, reg
        finally:
            server.shutdown()

    def test_models_predict_and_healthz(self, served):
        server, router, reg = served
        x = _rows(2).tolist()
        st, body, _ = _http(server.port, "POST", "/models/alpha/predict",
                            {"inputs": x})
        assert st == 200 and body["model_version"] == 1
        assert body["model"] == "alpha"
        # the payload-key spelling routes too
        st, body2, _ = _http(server.port, "POST", "/predict",
                             {"inputs": x, "model": "beta"})
        assert st == 200 and body2["model"] == "beta"
        assert body["outputs"] != body2["outputs"]
        st, hz, _ = _http(server.port, "GET", "/models/alpha/healthz")
        assert st == 200 and hz["active_version"] == 1 and hz["ready"]
        st, hz, _ = _http(server.port, "GET", "/healthz")
        assert st == 200 and "alpha" in hz["models"]

    def test_unknown_model_404(self, served):
        server, _, _ = served
        st, body, _ = _http(server.port, "POST", "/models/ghost/predict",
                            {"inputs": _rows(1).tolist()})
        assert st == 404 and body["error"] == "UnknownModelError"

    def test_tenant_quota_503_with_retry_after(self, served):
        server, router, _ = served
        mm = router.managed("alpha")
        orig = mm.active.engine.infer_versioned

        def slow(x, mask=None):
            time.sleep(0.15)
            return orig(x, mask)

        mm.active.engine.infer_versioned = slow
        x = _rows(1).tolist()
        got_503 = None
        threads = []

        def fire():
            st, body, hdrs = _http(server.port, "POST",
                                   "/models/alpha/predict",
                                   {"inputs": x},
                                   headers={"X-Tenant": "noisy"})
            nonlocal got_503
            if st == 503:
                got_503 = (body, hdrs)

        for _ in range(10):
            t = threading.Thread(target=fire)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        mm.active.engine.infer_versioned = orig
        assert got_503 is not None, "quota never tripped"
        body, hdrs = got_503
        assert body["error"] == "TenantQuotaExceededError"
        assert body["tenant"] == "noisy"
        assert int(hdrs["Retry-After"]) >= 1

    def test_single_model_503_has_retry_after(self, tmp_path):
        # the existing single-model surface gains the header too
        from deeplearning4j_tpu.serving import InferenceEngine

        eng = InferenceEngine(_net(5))
        server = InferenceServer(engine=eng, port=0, batch_limit=2,
                                 max_wait_ms=50.0, queue_limit=1).start()
        try:
            orig = eng.infer_versioned

            def slow(x, mask=None):
                time.sleep(0.3)
                return orig(x, mask)

            eng.infer_versioned = slow
            x = _rows(1).tolist()
            results = []
            threads = []

            def fire():
                results.append(_http(server.port, "POST", "/predict",
                                     {"inputs": x}))

            for _ in range(12):
                t = threading.Thread(target=fire)
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
            rejected = [(st, b, h) for st, b, h in results if st == 503]
            assert rejected, "queue_limit=1 never overflowed"
            st, body, hdrs = rejected[0]
            assert body["error"] == "ServerOverloadedError"
            assert int(hdrs["Retry-After"]) >= 1
        finally:
            server.shutdown()

    def test_reload_409_in_registry_mode(self, served):
        server, _, _ = served
        st, body, _ = _http(server.port, "POST", "/reload", {})
        assert st == 409 and "registry" in body["message"]


# ---------------------------------------------------------------------------
# the acceptance drill
# ---------------------------------------------------------------------------
class TestCanaryDrill:
    def test_live_fit_nan_and_regressed_snapshots_drill(self, tmp_path,
                                                        capsys):
        """ISSUE 11 acceptance: from a live fit, publish a NaN-poisoned
        and a score-regressed snapshot. The NaN one is REFUSED by the
        validation gate; the regressed one (slipping validation — the
        gap canaries exist for) is canaried and AUTO-ROLLED BACK by the
        serving-side score gate. Serving never returns a result from
        the bad version after regression_trip, in-flight old-version
        requests all complete, and cli flight-dump renders the ordered
        publish → canary_start → regression_trip → rollback timeline."""
        reg = ModelRegistry(str(tmp_path / "reg"))
        batches = _batches(5)
        train, val = batches[:-1], batches[-1:]
        model = _net(21)
        listener = RegistryPublishListener(
            str(tmp_path / "ck"), reg, "drill",
            validator=DataSetLossCalculator(
                ExistingDataSetIterator(val)).calculate_score,
            save_every_n_epochs=1, keep_mode="last", keep_last=3)
        model.add_listeners(listener)
        # the live fit: 2 epochs → 2 checkpoint-cadence publishes
        model.fit(ExistingDataSetIterator(train), epochs=2)
        assert len(listener.published) == 2
        good_versions = {r["version"] for r in listener.published}
        assert reg.get("drill")["active_version"] == 1
        # operator-promote the latest validated version before serving:
        # otherwise the router would (correctly) canary v2 first and the
        # bad publish below would queue behind that 30s window
        reg.activate("drill", 2)

        # NaN-poisoned snapshot from the live model: the validation
        # step scores NaN → refused typed, journaled rejected
        poisoned = _net(21)
        poisoned.params_ = jax.tree_util.tree_map(
            lambda a: np.full_like(np.asarray(a), np.nan), model.params_)
        nan_path = save_checkpoint(poisoned, str(tmp_path / "ck_nan"))
        rec = listener.publish(poisoned, nan_path, iteration=99)
        assert rec is None
        assert len(listener.refused) == 1
        nan_version = max(int(v)
                          for v in reg.get("drill")["versions"])
        assert reg.get("drill")["versions"][str(nan_version)]["status"] \
            == "rejected"

        # serve the model; the regressed snapshot passes the publish
        # gate (score marginally better — the validation-gap case) and
        # the canary score probe is what catches it
        bad_version = []

        def probe(engine):
            src = str(engine.describe()["source"])
            if bad_version and f"v{bad_version[0]:04d}" in src:
                return 9.0
            return 0.4

        router = ModelRouter(reg, batch_limit=8, max_wait_ms=1.0,
                             canary_fraction=0.5, canary_window_s=30.0,
                             score_probe=probe, score_trip_tolerance=0.5,
                             refresh_s=0.01)
        try:
            x = _rows(2)
            _, v0 = router.predict("drill", x, timeout=30)
            assert v0 in good_versions
            # in-flight old-version requests at trip time must complete:
            # slow the ACTIVE engine and keep requests in its pipe
            mm = router.managed("drill")
            orig = mm.active.engine.infer_versioned

            def slow(x, mask=None):
                time.sleep(0.05)
                return orig(x, mask)

            mm.active.engine.infer_versioned = slow
            inflight = [router.submit("drill", x, timeout=60)
                        for _ in range(4)]
            scrambled = _net(77)  # same arch, junk weights
            bad_path = save_checkpoint(scrambled, str(tmp_path / "ck_bad"))
            seq0 = flight.default_flight_recorder().recorded_total
            best = reg.best_score("drill")
            rec = reg.publish("drill", bad_path, score=best * 0.99)
            bad_version.append(rec["version"])
            served = []
            deadline = time.monotonic() + 30
            rolled = False
            while time.monotonic() < deadline:
                try:
                    _, v = router.predict("drill", x, timeout=30)
                    served.append(v)
                except CanaryRolledBackError:
                    pass
                if (reg.get("drill")["versions"][str(rec["version"])]
                        ["status"] == "rolled_back"):
                    rolled = True
                    break
            assert rolled, "regressed canary never rolled back"
            # serving never returned a bad-version result
            assert rec["version"] not in set(served)
            # the in-flight old-version requests all completed
            for r in inflight:
                out = r.result(timeout=60)
                assert out.shape == (2, N_OUT)
                assert r.model_version in good_versions
            mm2 = router.managed("drill")
            if mm2.active is mm.active:
                mm.active.engine.infer_versioned = orig
            # the ordered deployment timeline, publish first
            tl = _flight_kinds(seq0, {"publish", "canary_start",
                                      "regression_trip", "rollback"})
            kinds = [k for _, k, _ in tl]
            assert kinds == ["publish", "canary_start",
                             "regression_trip", "rollback"], kinds
            seqs = [s for s, _, _ in tl]
            assert seqs == sorted(seqs)
        finally:
            router.shutdown()

        # cli flight-dump renders the timeline from the dumped black box
        from deeplearning4j_tpu.cli import flight_dump_main

        dump_path = flight.default_flight_recorder().dump(
            path=str(tmp_path / "flight.json"), reason="drill")
        assert dump_path is not None
        assert flight_dump_main([dump_path]) == 0
        out = capsys.readouterr().out
        order = [out.index(k) for k in ("publish", "canary_start",
                                        "regression_trip", "rollback")]
        assert order == sorted(order)


# ---------------------------------------------------------------------------
# retry-after units
# ---------------------------------------------------------------------------
class TestRetryAfter:
    def test_batcher_estimate_clamped(self):
        from deeplearning4j_tpu.serving import DynamicBatcher

        gate = threading.Event()

        def dispatch(reqs):
            gate.wait(30)
            for r in reqs:
                r.finish(r.x)

        b = DynamicBatcher(dispatch, batch_limit=1, max_wait_ms=1.0,
                           queue_limit=8)
        try:
            assert b.retry_after_s() == 1.0  # no history → 1s floor
            reqs = [b.submit(np.zeros((1, 2), np.float32))
                    for _ in range(3)]
            deadline = time.monotonic() + 10
            while b.queue_depth() != 2 and time.monotonic() < deadline:
                time.sleep(0.005)  # one in the gated dispatch, 2 queued
            b._dispatch_ewma_s = 10.0
            assert b.retry_after_s() == 20.0  # 2 queued × 10s
            b._dispatch_ewma_s = 100.0
            assert b.retry_after_s() == 60.0  # 60s cap
            gate.set()
            for r in reqs:
                r.result(timeout=30)
        finally:
            gate.set()
            b.shutdown()

    def test_generation_overload_carries_retry_after(self):
        # covered end-to-end in test_generate.py overload tests; here:
        # the typed error's hint surface exists and clamps
        from deeplearning4j_tpu.serving.generate import GenerationEngine

        assert hasattr(GenerationEngine, "retry_after_s")


def teardown_module(module):
    gc.collect()
