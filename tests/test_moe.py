"""Mixture-of-Experts + expert parallelism (NEW capability; SURVEY.md
§2.5 lists EP as ABSENT in the reference — added here like TP/PP/SP).

Covers: dense-dispatch routing invariants, training (aux loss plumbed
through MLN and CG), serde round-trip, and EP-vs-single-device parity on
the 8-device CPU mesh.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    MixtureOfExpertsLayer,
    MoETransformerBlock,
    OutputLayer,
    PositionalEmbeddingLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.moe import _moe_dispatch
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.updaters import Adam


# The PP/SP compositions below ran for 20 PRs as strict xfails: jax-0.4.37's
# legacy shard_map cannot mix manual and auto mesh axes in this program
# family (_SpecError on scalar out-specs, XLA PartitionId UNIMPLEMENTED, a
# spmd_partitioner CHECK crash). The manual regions are now FULLY manual over
# every mesh axis with explicit TP/EP collectives (parallel/transformer
# ``_blocks_fn``), so the markers are retired and every mesh shape is
# exercised for real — including the exact-parity assertions.


def _mlp_moe_conf(n_in=8, n_experts=4, top_k=2, seed=0, cf=2.0):
    return (
        NeuralNetConfiguration.builder().seed(seed)
        .updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=16, activation="relu"))
        .layer(MixtureOfExpertsLayer(n_experts=n_experts, top_k=top_k,
                                     capacity_factor=cf))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(n_in))
        .build()
    )


class TestMoEDispatch:
    def test_dispatch_invariants(self):
        rng = np.random.default_rng(0)
        probs = jax.nn.softmax(jnp.asarray(rng.standard_normal((32, 4)),
                                           jnp.float32), -1)
        dispatch, combine, aux, load = _moe_dispatch(probs, capacity=32, top_k=2)
        # every token assigned to exactly top_k expert slots (capacity ample)
        np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 2.0)
        # each expert slot holds at most one token
        assert float(dispatch.sum(0).max()) <= 1.0 + 1e-6
        # combine weights normalized per token
        np.testing.assert_allclose(np.asarray(combine.sum((1, 2))), 1.0,
                                   atol=1e-5)
        # aux loss near 1 for near-uniform routing, >= 1 always
        assert 0.9 < float(aux) < 4.0

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0 with capacity 2: only 2 dispatched
        probs = jnp.asarray(np.tile([0.97, 0.01, 0.01, 0.01], (10, 1)),
                            jnp.float32)
        dispatch, _, _, _ = _moe_dispatch(probs, capacity=2, top_k=1)
        assert float(dispatch[:, 0].sum()) == 2.0
        assert float(dispatch.sum()) == 2.0


class TestMoELayerTraining:
    def test_mln_trains_and_aux_loss_in_score(self):
        conf = _mlp_moe_conf()
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[(np.abs(x[:, 0]) * 3).astype(int) % 3]
        first = None
        for _ in range(30):
            net.fit(DataSet(x, y), epochs=1, batch_size=64)
            if first is None:
                first = float(net.score_)
        assert np.isfinite(float(net.score_))
        assert float(net.score_) < first, "MoE MLP failed to learn"

    def test_eval_path_deterministic_no_aux(self):
        net = MultiLayerNetwork(_mlp_moe_conf()).init()
        x = np.random.default_rng(2).standard_normal((8, 8)).astype(np.float32)
        o1, o2 = net.output(x), net.output(x)
        np.testing.assert_allclose(o1, o2)
        assert o1.shape == (8, 3)

    def test_moe_transformer_block_cg_sequence(self):
        conf = (
            NeuralNetConfiguration.builder().seed(3)
            .updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(12, 6))
            .add_layer("pos", PositionalEmbeddingLayer(), "in")
            .add_layer("moe", MoETransformerBlock(n_heads=2, n_experts=4,
                                                  capacity_factor=2.0), "pos")
            .add_layer("out", RnnOutputLayer(n_out=5, activation="softmax",
                                             loss="mcxent"), "moe")
            .set_outputs("out")
            .build()
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(4)
        x = rng.standard_normal((8, 6, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, (8, 6))]
        ds = DataSet(x, y)
        scores = []
        for _ in range(15):
            net.fit(ds, batch_size=8)
            scores.append(float(net.score_))
        assert np.isfinite(scores[-1]) and scores[-1] < scores[0]

    def test_serde_round_trip(self):
        conf = _mlp_moe_conf(n_experts=8, top_k=1)
        c2 = type(conf).from_json(conf.to_json())
        moe = c2.layers[1]
        assert isinstance(moe, MixtureOfExpertsLayer)
        assert moe.n_experts == 8 and moe.top_k == 1
        net = MultiLayerNetwork(c2).init()
        x = np.zeros((2, 8), np.float32)
        assert net.output(x).shape == (2, 3)


class TestExpertParallel:
    def test_ep_matches_single_device(self):
        """EP on a (data=4, expert=2) mesh must train bit-compatibly with
        the unsharded step (same math, different layout)."""
        from deeplearning4j_tpu.parallel import ExpertParallelWrapper, TrainingMesh

        rng = np.random.default_rng(5)
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]

        ref = MultiLayerNetwork(_mlp_moe_conf(seed=9)).init()
        for _ in range(5):
            ref.fit(DataSet(x, y), epochs=1, batch_size=32)
        ref_score = float(ref.score_)

        ep_net = MultiLayerNetwork(_mlp_moe_conf(seed=9)).init()
        mesh = TrainingMesh(data=4, expert=2)
        wrap = ExpertParallelWrapper(ep_net, mesh).place()
        for _ in range(5):
            ep_score = wrap.fit_batch(x, y)

        np.testing.assert_allclose(ep_score, ref_score, rtol=1e-4)
        # params converged identically
        for p_ref, p_ep in zip(ref.params_, ep_net.params_):
            for k in p_ref:
                np.testing.assert_allclose(
                    np.asarray(p_ref[k]), np.asarray(p_ep[k]), rtol=2e-4,
                    atol=1e-5, err_msg=k)

    def test_expert_params_actually_sharded(self):
        from deeplearning4j_tpu.parallel import ExpertParallelWrapper, TrainingMesh

        net = MultiLayerNetwork(_mlp_moe_conf(seed=11)).init()
        mesh = TrainingMesh(data=4, expert=2)
        ExpertParallelWrapper(net, mesh).place()
        w1 = net.params_[1]["W1"]
        specs = w1.sharding.spec
        assert specs[0] == "expert", f"W1 not expert-sharded: {specs}"
        # gate stays replicated
        assert net.params_[1]["Wg"].sharding.spec == ()

    def test_indivisible_experts_rejected(self):
        from deeplearning4j_tpu.parallel import ExpertParallelWrapper, TrainingMesh

        net = MultiLayerNetwork(_mlp_moe_conf(n_experts=3)).init()
        mesh = TrainingMesh(data=4, expert=2)
        with pytest.raises(ValueError, match="not divisible"):
            ExpertParallelWrapper(net, mesh)


class TestMoEMasking:
    def test_masked_tokens_take_no_capacity_and_skip_aux(self):
        """Padding tokens must not consume expert capacity slots nor bias
        the load-balancing statistics."""
        rng = np.random.default_rng(7)
        probs = jax.nn.softmax(
            jnp.asarray(rng.standard_normal((12, 4)), jnp.float32), -1)
        valid = jnp.asarray([1] * 6 + [0] * 6, jnp.float32)
        dispatch, combine, aux, _ = _moe_dispatch(probs, capacity=8, top_k=2,
                                                  valid=valid)
        # masked tokens dispatched nowhere, combine weight zero
        assert float(dispatch[6:].sum()) == 0.0
        assert float(combine[6:].sum()) == 0.0
        # valid tokens still fully routed
        np.testing.assert_allclose(np.asarray(dispatch[:6].sum((1, 2))), 2.0)
        # aux computed over the 6 valid tokens only: same as an unmasked
        # call on just those tokens
        _, _, aux_ref, _ = _moe_dispatch(probs[:6], capacity=8, top_k=2)
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


class TestMoETbptt:
    def test_aux_loss_included_in_tbptt_score(self):
        """The tBPTT step must add the MoE aux loss exactly like the
        standard step: with a huge aux_loss_weight the tBPTT score must
        visibly exceed the pure data loss."""
        def conf(aux_w):
            return (
                NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(1e-3))
                .list()
                .layer(MixtureOfExpertsLayer(n_experts=4, top_k=2,
                                             capacity_factor=2.0,
                                             aux_loss_weight=aux_w))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .backprop_type("tbptt", fwd_length=4, back_length=4)
                .set_input_type(InputType.recurrent(8, 8))
                .build()
            )

        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 8))]

        def first_score(aux_w):
            net = MultiLayerNetwork(conf(aux_w)).init()
            net.fit(DataSet(x, y), batch_size=4)
            return float(net.score_)

        s_small, s_huge = first_score(1e-8), first_score(100.0)
        # aux >= 1 by construction, so weight 100 must add ~>=100
        assert s_huge > s_small + 50.0, (s_small, s_huge)


class TestMoETransformerLM:
    """MoE TransformerLM: dense-dispatch expert FFN in the flagship model,
    EP composed with DP/TP (GShard layout) in the distributed trainer."""

    def _data(self, V=32, B=8, T=8, seed=0):
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, V, (B, T)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tgt[:, -1] = -1
        return ids, tgt

    def test_single_device_moe_lm_trains(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, n_experts=4,
                          capacity_factor=2.0).init()
        assert m.params_["blocks"]["W1"].shape == (2, 4, 32, 128)
        ids, tgt = self._data()
        losses = [m.fit_batch(ids, tgt) for _ in range(12)]
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
        # generate still works under MoE
        out = m.generate(ids[:1, :4], max_new=3)
        assert out.shape == (1, 7)

    def test_distributed_ep_tp_dp_matches_single(self):
        """(data=2, model=2, expert=2) mesh step == unsharded step."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        ids, tgt = self._data()

        def make():
            return TransformerLM(vocab_size=32, d_model=32, n_heads=4,
                                 n_layers=2, max_length=8, n_experts=4,
                                 capacity_factor=2.0, seed=5).init()

        ref = make()
        ref_losses = [ref.fit_batch(ids, tgt) for _ in range(4)]

        dist = make()
        mesh = TrainingMesh(data=2, model=2, expert=2)
        tr = DistributedLMTrainer(dist, mesh).place()
        dist_losses = [tr.fit_batch(ids, tgt) for _ in range(4)]

        np.testing.assert_allclose(dist_losses, ref_losses, rtol=2e-4)
        # expert params really sharded over the expert axis
        spec = dist.params_["blocks"]["W1"].sharding.spec
        assert "expert" in spec

    def test_moe_pipeline_with_expert_axis_matches_single_device(self):
        """PP×EP composes (VERDICT r4 #4): expert params stay partitioned
        over 'expert' (an auto axis inside the pipeline's manual
        shard_map), the dispatch einsums lower to the token all-to-all,
        and with one microbatch the loss matches single-device exactly."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        ids, tgt = self._data()

        def make():
            return TransformerLM(vocab_size=32, d_model=32, n_heads=4,
                                 n_layers=2, max_length=8, n_experts=4,
                                 capacity_factor=2.0, seed=5).init()

        ref = make()
        ref_losses = [ref.fit_batch(ids, tgt) for _ in range(3)]
        dist = make()
        tr = DistributedLMTrainer(
            dist, TrainingMesh(data=2, pipe=2, expert=2), n_micro=1).place()
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
        # expert params really sharded over the expert axis under PP
        spec = dist.params_["blocks"]["W1"].sharding.spec
        assert "expert" in spec and "pipe" in spec

    def test_moe_pipeline_with_expert_axis_microbatched(self):
        """PP×EP with real microbatching (per-microbatch routing + aux
        grad-accumulation semantics) trains finitely."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        ids, tgt = self._data()
        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, n_experts=4, capacity_factor=2.0,
                          seed=5).init()
        tr = DistributedLMTrainer(
            m, TrainingMesh(data=2, pipe=2, expert=2), n_micro=2).place()
        losses = [tr.fit_batch(ids, tgt) for _ in range(4)]
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_pipeline_matches_single_device(self):
        """PP + MoE (r4): with one microbatch the routing batch equals
        the single-device one, so losses agree exactly; the aux scalar
        accumulates around the ring."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        ids, tgt = self._data()

        def make():
            return TransformerLM(vocab_size=32, d_model=32, n_heads=4,
                                 n_layers=2, max_length=8, n_experts=4,
                                 capacity_factor=2.0, seed=5).init()

        ref = make()
        ref_losses = [ref.fit_batch(ids, tgt) for _ in range(3)]
        tr = DistributedLMTrainer(make(), TrainingMesh(data=4, pipe=2),
                                  n_micro=1).place()
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)

    def test_moe_pipeline_microbatched_trains(self):
        """PP + MoE with real microbatching: per-microbatch routing and
        aux (grad-accumulation semantics) — converges finitely."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        ids, tgt = self._data()
        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, n_experts=4, capacity_factor=2.0,
                          seed=5).init()
        tr = DistributedLMTrainer(m, TrainingMesh(data=4, pipe=2),
                                  n_micro=4).place()
        losses = [tr.fit_batch(ids, tgt) for _ in range(8)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_moe_sp_composes(self):
        """EP + SP: ring attention over "seq" with per-shard routing.

        Runs in a SUBPROCESS (tests/moe_sp_worker.py): executing this
        seq-manual x expert-auto program after many prior programs in
        the same process can raw-SIGABRT in the jaxlib 0.9.0 CPU
        runtime (flaky, prior-state-dependent — the identical program
        passes deterministically in a fresh process; r4 bisect)."""
        import subprocess
        import sys

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "moe_sp_worker.py")],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode == 0, (
            f"worker failed\nstdout:\n{proc.stdout[-3000:]}\n"
            f"stderr:\n{proc.stderr[-3000:]}")
        assert "ALL-OK" in proc.stdout


class TestLMMixedPrecision:
    def test_bf16_lm_trajectory_tracks_fp32(self):
        """compute_dtype="bfloat16": fp32 master params, bf16 matmuls —
        loss trajectory must track the fp32 run within bf16 tolerance,
        and params must stay fp32."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (8, 16)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tgt[:, -1] = -1

        def run(cd):
            m = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, max_length=16, seed=7,
                              compute_dtype=cd).init()
            losses = [m.fit_batch(ids, tgt) for _ in range(10)]
            assert m.params_["blocks"]["W1"].dtype == jnp.float32
            return losses

        f32, bf16 = run(None), run("bfloat16")
        assert bf16[-1] < bf16[0], "bf16 LM failed to learn"
        np.testing.assert_allclose(bf16, f32, rtol=0.06)

    def test_bf16_moe_lm_trains(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        rng = np.random.default_rng(1)
        ids = rng.integers(0, 64, (8, 16)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tgt[:, -1] = -1
        m = TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                          max_length=16, n_experts=4, capacity_factor=2.0,
                          compute_dtype="bfloat16", seed=2).init()
        losses = [m.fit_batch(ids, tgt) for _ in range(10)]
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]

    def test_bf16_distributed_trainer(self):
        """compute_dtype=bfloat16 must work through DistributedLMTrainer
        (scan carry stays bf16; fp32 final norm/logits)."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, compute_dtype="bfloat16",
                          seed=1).init()
        tr = DistributedLMTrainer(m, TrainingMesh(data=4, model=2)).place()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 32, (8, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
        assert m.params_["blocks"]["W1"].dtype == jnp.float32

    def test_invalid_compute_dtype_rejected(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        with pytest.raises(ValueError, match="compute_dtype"):
            TransformerLM(vocab_size=8, compute_dtype="bf16")

    def test_bf16_sp_ring_attention(self):
        """bf16 + sequence parallelism: the ring-attention kernel gets
        bf16 q/k/v but accumulates fp32 internally."""
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, compute_dtype="bfloat16",
                          seed=4).init()
        tr = DistributedLMTrainer(m, TrainingMesh(data=4, seq=2)).place()
        rng = np.random.default_rng(2)
        ids = rng.integers(0, 32, (8, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]


class TestLMSamplingAndPerplexity:
    def _model(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, seed=0).init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 32, (8, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        for _ in range(5):
            m.fit_batch(ids, tgt)
        return m, ids, tgt

    def test_top_k_restricts_to_k_candidates(self):
        m, ids, _ = self._model()
        prompt = ids[:1, :4]
        logits = m.logits(prompt)[:, -1]
        top2 = set(np.argsort(-logits[0])[:2].tolist())
        out = m.generate(prompt, max_new=1, temperature=1.0, top_k=2,
                         rng=jax.random.PRNGKey(3))
        assert int(out[0, -1]) in top2

    def test_top_p_nucleus_keeps_crossing_token(self):
        m, ids, _ = self._model()
        prompt = ids[:1, :4]
        # tiny p: nucleus is exactly the argmax token -> deterministic
        out1 = m.generate(prompt, max_new=3, temperature=1.0, top_p=1e-6,
                          rng=jax.random.PRNGKey(0))
        greedy = m.generate(prompt, max_new=3, temperature=0.0)
        np.testing.assert_array_equal(out1, greedy)

    def test_sampling_flags_need_temperature(self):
        m, ids, _ = self._model()
        with pytest.raises(ValueError, match="temperature"):
            m.generate(ids[:1, :4], max_new=1, top_k=3)

    def test_perplexity_decreases_with_training(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        rng = np.random.default_rng(1)
        ids = rng.integers(0, 16, (16, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        m = TransformerLM(vocab_size=16, d_model=32, n_heads=4, n_layers=2,
                          max_length=8, seed=4).init()
        before = m.perplexity(ids, tgt)
        # untrained ppl ~ vocab size for uniform predictions
        assert 8 < before < 40
        for _ in range(20):
            m.fit_batch(ids, tgt)
        after = m.perplexity(ids, tgt)
        assert after < before / 2

    def test_out_of_range_sampling_params_rejected(self):
        m, ids, _ = self._model()
        with pytest.raises(ValueError, match="top_k"):
            m.generate(ids[:1, :4], max_new=1, temperature=1.0, top_k=-2)
        with pytest.raises(ValueError, match="top_p"):
            m.generate(ids[:1, :4], max_new=1, temperature=1.0, top_p=1.5)


class TestExpertLoadObservability:
    def test_expert_load_in_state_sums_to_one(self):
        net = MultiLayerNetwork(_mlp_moe_conf()).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.fit(DataSet(x, y), epochs=1, batch_size=32)
        load = np.asarray(net.state_[1]["expert_load"])
        assert load.shape == (4,)
        np.testing.assert_allclose(load.sum(), 1.0, atol=1e-5)
        assert (load >= 0).all()


class TestKVCacheDecoding:
    def _trained(self, **kw):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                          max_length=16, seed=0, **kw).init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 32, (8, 16)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1
        for _ in range(5):
            m.fit_batch(ids, tgt)
        return m, ids

    def test_greedy_parity_with_full_forward(self):
        m, ids = self._trained()
        prompt = ids[:2, :5]
        full = m.generate(prompt, max_new=8)
        cached = m.generate_cached(prompt, max_new=8)
        np.testing.assert_array_equal(full, cached)

    def test_greedy_parity_bf16(self):
        m, ids = self._trained(compute_dtype="bfloat16")
        prompt = ids[:2, :4]
        np.testing.assert_array_equal(
            m.generate(prompt, max_new=6),
            m.generate_cached(prompt, max_new=6))

    def test_greedy_parity_moe(self):
        m, ids = self._trained(n_experts=4, capacity_factor=2.0)
        prompt = ids[:1, :4]
        np.testing.assert_array_equal(
            m.generate(prompt, max_new=6),
            m.generate_cached(prompt, max_new=6))

    def test_sampled_parity_same_rng(self):
        m, ids = self._trained()
        prompt = ids[:1, :4]
        a = m.generate(prompt, max_new=6, temperature=0.8, top_k=5,
                       rng=jax.random.PRNGKey(7))
        b = m.generate_cached(prompt, max_new=6, temperature=0.8, top_k=5,
                              rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(a, b)

    def test_overflow_rejected(self):
        m, ids = self._trained()
        with pytest.raises(ValueError, match="max_length"):
            m.generate_cached(ids[:1, :10], max_new=10)
