"""Checkpoint-format regression tests (reference
``regressiontest/RegressionTest{050..080}.java``, SURVEY.md §4.3: model
zips produced by OLDER versions must keep deserializing and predicting).

The fixtures under tests/fixtures/regression/ were produced by the v1
(round-3) serializer and are COMMITTED — do not regenerate them when the
format changes; make the loader handle old files instead. That is the
entire point of this suite.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.train.model_serializer import ModelGuesser, ModelSerializer

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "regression")


class TestV1CheckpointFormat:
    def test_cnn_bn_adam_roundtrip(self):
        """Config + coefficients + Adam updater state + BN running stats
        all restore; outputs match the recorded goldens exactly."""
        net = ModelSerializer.restore_multi_layer_network(
            os.path.join(FIXTURES, "cnn_bn_adam_v1.zip")
        )
        g = np.load(os.path.join(FIXTURES, "cnn_bn_adam_v1_golden.npz"))
        np.testing.assert_allclose(net.output(g["x"]), g["y"], atol=1e-6)
        assert net.iteration == int(g["iteration"])
        # updater state restored (non-trivial Adam moments)
        assert net.opt_state_ is not None
        flat = net.opt_state_flat()
        assert flat.size > 0 and np.abs(flat).max() > 0

    def test_cnn_training_resumes(self):
        """A restored v1 checkpoint keeps training (updater state is
        live, not just stored)."""
        from deeplearning4j_tpu.data.dataset import DataSet

        net = ModelSerializer.restore_multi_layer_network(
            os.path.join(FIXTURES, "cnn_bn_adam_v1.zip")
        )
        rng = np.random.default_rng(1)
        x = rng.standard_normal((16, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        it0 = net.iteration
        net.fit(DataSet(x, y), epochs=1, batch_size=8)
        assert net.iteration == it0 + 2
        assert np.isfinite(net.score())

    def test_lstm_roundtrip(self):
        net = ModelSerializer.restore_multi_layer_network(
            os.path.join(FIXTURES, "lstm_adam_v1.zip")
        )
        g = np.load(os.path.join(FIXTURES, "lstm_adam_v1_golden.npz"))
        np.testing.assert_allclose(net.output(g["x"]), g["y"], atol=1e-6)

    def test_model_guesser(self):
        """ModelGuesser sniffs MLN zips (reference ``ModelGuesser.java``)."""
        m = ModelGuesser.load_model_guess(
            os.path.join(FIXTURES, "cnn_bn_adam_v1.zip")
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        assert isinstance(m, MultiLayerNetwork)


class TestV4CheckpointFormat:
    """Round-4 format additions: a ComputationGraph containing a
    FusedResNetBottleneck (multi-conv params + several BN running-stat
    pairs in ONE layer state dict) must keep loading in every future
    round."""

    def test_fused_block_roundtrip(self):
        net = ModelSerializer.restore_computation_graph(
            os.path.join(FIXTURES, "fused_block_adam_v4.zip")
        )
        g = np.load(os.path.join(FIXTURES, "fused_block_adam_v4_golden.npz"))
        np.testing.assert_allclose(
            np.asarray(net.output_single(g["x"])), g["y"], atol=1e-6)
        assert net.iteration == int(g["iteration"])
        # the block's BN running stats restored as layer state
        st = net.state_["block"]
        assert "mean_c" in st and np.abs(np.asarray(st["mean_c"])).max() > 0

    def test_fused_block_training_resumes(self):
        from deeplearning4j_tpu.data.dataset import DataSet

        net = ModelSerializer.restore_computation_graph(
            os.path.join(FIXTURES, "fused_block_adam_v4.zip")
        )
        rng = np.random.default_rng(2)
        x = rng.standard_normal((8, 8, 8, 16)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        it0 = net.iteration
        net.fit(DataSet(x, y), epochs=1, batch_size=8)
        assert net.iteration == it0 + 1
        assert np.isfinite(float(net.score_))
