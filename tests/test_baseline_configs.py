"""End-to-end tests for the BASELINE.json named configs that became
runnable in round 3 — most notably config #3, "deeplearning4j-nlp:
Word2Vec + LSTM sentiment (ComputationGraph)": pretrained word vectors
feed an LSTM sentiment classifier built as a ComputationGraph.

(Config #1 LeNet/MNIST and #4 Keras import are covered by
tests/test_zoo.py and tests/test_keras_import.py; #2/#5 run in bench.py
and the multichip dryrun.)
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nlp import Word2Vec
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import LSTM, OutputLayer
from deeplearning4j_tpu.nn.conf.graph_vertices import LastTimeStepVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.updaters import Adam

POSITIVE = ["great", "excellent", "wonderful", "love", "amazing", "happy"]
NEGATIVE = ["terrible", "awful", "horrible", "hate", "boring", "sad"]
NEUTRAL = ["movie", "film", "plot", "acting", "scene", "story", "the", "was"]


def sentiment_corpus(n=400, max_len=8, seed=13):
    """Synthetic reviews: sentiment words + neutral filler."""
    rng = np.random.default_rng(seed)
    sents, labels = [], []
    for _ in range(n):
        pos = rng.random() < 0.5
        opinion = rng.choice(POSITIVE if pos else NEGATIVE,
                             rng.integers(1, 3))
        filler = rng.choice(NEUTRAL, rng.integers(3, max_len - 2))
        words = list(opinion) + list(filler)
        rng.shuffle(words)
        sents.append(" ".join(words))
        labels.append(1 if pos else 0)
    return sents, np.asarray(labels)


class TestWord2VecLstmSentiment:
    @pytest.mark.slow
    def test_config3_end_to_end(self):
        sents, labels = sentiment_corpus()
        # ---- phase 1: unsupervised Word2Vec on the corpus
        w2v = (
            Word2Vec.builder().iterate(sents).layer_size(16).window_size(3)
            .min_word_frequency(2).seed(7).learning_rate(0.05).epochs(5)
            .batch_size(256).negative_sample(5).build().fit()
        )
        D = 16
        T = 10

        def embed(sentence):
            vecs = [
                w2v.get_word_vector(t)
                for t in sentence.split() if w2v.has_word(t)
            ]
            out = np.zeros((T, D), np.float32)
            msk = np.zeros((T,), np.float32)
            for i, v in enumerate(vecs[:T]):
                out[i] = v
                msk[i] = 1.0
            return out, msk

        X = np.zeros((len(sents), T, D), np.float32)
        M = np.zeros((len(sents), T), np.float32)
        for i, s in enumerate(sents):
            X[i], M[i] = embed(s)
        Y = np.eye(2, dtype=np.float32)[labels]

        # ---- phase 2: LSTM sentiment ComputationGraph on the embeddings
        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .weight_init("xavier").graph_builder()
            .add_inputs("tokens")
            .add_layer("lstm", LSTM(n_out=16, activation="tanh"), "tokens")
            .add_vertex("last", LastTimeStepVertex(mask_input="tokens"), "lstm")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "last")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(D, T))
            .build()
        )
        net = ComputationGraph(conf).init()
        tr = DataSet(X[:320], Y[:320], M[:320])
        te = DataSet(X[320:], Y[320:], M[320:])
        acc = 0.0
        for _ in range(30):
            net.fit(tr, batch_size=64)
            acc = net.evaluate(te).accuracy()
            if acc >= 0.9:
                break
        assert acc >= 0.9, f"sentiment accuracy {acc:.3f} < 0.9"


class TestCliAndParallelEarlyStopping:
    def test_cli_trains_and_saves(self, tmp_path):
        """ParallelWrapperMain-equivalent CLI: train, checkpoint,
        dashboard (reference parallelism/main/ParallelWrapperMain.java)."""
        from deeplearning4j_tpu.cli import main

        out = str(tmp_path / "m.zip")
        dash = str(tmp_path / "d.html")
        rc = main([
            "--model", "lenet", "--dataset", "mnist", "--epochs", "1",
            "--batch-size", "32", "--num-examples", "64",
            "--output", out, "--dashboard", dash,
        ])
        assert rc == 0
        import os

        assert os.path.exists(out) and os.path.exists(dash)
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = ModelSerializer.restore_multi_layer_network(out)
        assert net.iteration == 2

    def test_cli_parallel_workers(self, tmp_path):
        from deeplearning4j_tpu.cli import main

        rc = main([
            "--model", "lenet", "--dataset", "mnist", "--epochs", "1",
            "--batch-size", "32", "--num-examples", "64", "--workers", "8",
        ])
        assert rc == 0

    def test_early_stopping_parallel_trainer(self):
        """EarlyStoppingParallelTrainer: early stopping over
        data-parallel epochs (reference EarlyStoppingParallelTrainer)."""
        import numpy as np

        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train.earlystopping import (
            DataSetLossCalculator,
            EarlyStoppingConfiguration,
            EarlyStoppingParallelTrainer,
            InMemoryModelSaver,
            MaxEpochsTerminationCondition,
        )

        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
        ds = DataSet(x, y)
        conf = (
            NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build()
        )
        net = MultiLayerNetwork(conf).init()
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(ds, 32)))
            .model_saver(InMemoryModelSaver())
            .build()
        )
        trainer = EarlyStoppingParallelTrainer(
            cfg, net, ListDataSetIterator(ds, 32)
        )
        result = trainer.fit()
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.total_epochs == 5
        assert np.isfinite(result.best_model_score)


class TestCliPrecisionFlags:
    def test_cli_bf16_and_remat_flags(self, tmp_path):
        from deeplearning4j_tpu.cli import main

        out = str(tmp_path / "m.zip")
        rc = main([
            "--model", "lenet", "--dataset", "mnist", "--epochs", "1",
            "--batch-size", "32", "--num-examples", "64", "--output", out,
            "--compute-dtype", "bfloat16", "--remat-policy",
            "save_conv_outputs",
        ])
        assert rc == 0
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = ModelSerializer.restore_multi_layer_network(out)
        assert net.conf.global_conf.compute_dtype == "bfloat16"
        assert net.conf.global_conf.remat_policy == "save_conv_outputs"
