"""EP+SP composition check (ring attention over "seq" with per-shard MoE
routing), run in its OWN process by test_moe.py.

Why: executing this specific program shape — shard_map manual over
{"seq"} combined with the auto-sharded "expert" axis — after many prior
program executions in the same process can raw-SIGABRT inside the
jaxlib 0.9.0 CPU runtime (no error message; `array._value` during the
host sync). It is a flaky, prior-state-dependent runtime crash, not a
correctness problem: the identical test passes deterministically in a
fresh process (and passed in full-suite runs whose preceding test set
differed). Bisected in round 4 after a stale cross-machine compilation
cache produced the same symptom for a different reason.

Exit 0 = losses finite and decreasing.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.models.transformer_lm import TransformerLM
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

V = 32
rng = np.random.default_rng(0)
ids = rng.integers(0, V, (8, 8)).astype(np.int32)
tgt = np.roll(ids, -1, axis=1).astype(np.int32)
tgt[:, -1] = -1

m = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=2,
                  max_length=8, n_experts=2, capacity_factor=2.0,
                  seed=3).init()
mesh = TrainingMesh(data=2, seq=2, expert=2)
tr = DistributedLMTrainer(m, mesh).place()
losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses
print(f"EP+SP composes: losses {losses}", flush=True)
print("ALL-OK", flush=True)
