"""Generate the pretrained-weight fixture artifact (VERDICT r3 item 7):
a seeded, briefly-trained LeNet saved in the reference zip checkpoint
layout + golden outputs, so ``ZooModel.init_pretrained(path=...)`` has an
offline round-trip test (reference ``ZooModel.initPretrained`` +
checksum, ``ZooModel.java:40-62``).

Run once: python tests/fixtures/gen_zoo_pretrained_fixture.py
Writes zoo/lenet_synthmnist.zip + zoo/lenet_synthmnist_golden.npz +
prints the sha256 to paste into the test.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "zoo")


def main():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.mnist import synthetic_mnist
    from deeplearning4j_tpu.models.lenet import LeNet
    from deeplearning4j_tpu.models.zoo import ZooModel
    from deeplearning4j_tpu.train.model_serializer import ModelSerializer

    os.makedirs(OUT, exist_ok=True)
    net = LeNet(num_classes=10, seed=1234).init()
    imgs, labels = synthetic_mnist(256, seed=11)
    net.fit(DataSet(imgs.astype(np.float32),
                    np.eye(10, dtype=np.float32)[labels]),
            epochs=2, batch_size=64)

    path = os.path.join(OUT, "lenet_synthmnist.zip")
    ModelSerializer.write_model(net, path, save_updater=False)
    x = imgs[:8].astype(np.float32)
    y = np.asarray(net.output(x))
    np.savez(os.path.join(OUT, "lenet_synthmnist_golden.npz"), x=x, y=y)
    print(f"wrote {path} ({os.path.getsize(path)//1024} KB)")
    print("sha256:", ZooModel._sha256(path))


if __name__ == "__main__":
    main()
