"""Generate Keras HDF5 golden fixtures for model-import tests.

Mirrors the reference's fixture pattern: in-tree Python scripts produce
HDF5 models + golden outputs that the import tests assert against
(``deeplearning4j-modelimport/src/test/.../weights/scripts/``, the 11
in-tree .py files; SURVEY.md §4.7).

Run once (Keras 3 / TF backend, both baked in the image):
    python tests/fixtures/gen_keras_fixtures.py
Writes <name>.h5 + <name>_golden.npz (input, output) next to this file.
Models are tiny (fixed seeds) so the fixtures stay a few hundred KB.
"""

import os
import sys

os.environ["CUDA_VISIBLE_DEVICES"] = "-1"
os.environ.setdefault("KERAS_BACKEND", "tensorflow")

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "keras")


def main():
    import numpy as np
    import keras
    from keras import layers

    os.makedirs(OUT, exist_ok=True)
    rng = np.random.default_rng(1234)

    def save(name, model, x):
        keras.utils.set_random_seed(0)
        path = os.path.join(OUT, f"{name}.h5")
        model.save(path)
        y = model.predict(x, verbose=0)
        np.savez(os.path.join(OUT, f"{name}_golden.npz"), x=x, y=y)
        print(f"{name}: {path} ({os.path.getsize(path)//1024} KB), out {y.shape}")

    keras.utils.set_random_seed(7)

    # 1. Sequential MLP
    m = keras.Sequential([
        keras.Input((12,)),
        layers.Dense(16, activation="relu"),
        layers.Dense(8, activation="tanh"),
        layers.Dense(4, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    save("mlp", m, rng.standard_normal((5, 12)).astype(np.float32))

    # 2. Sequential CNN (conv/bn/pool/flatten/dense) — LeNet-ish
    m = keras.Sequential([
        keras.Input((12, 12, 3)),
        layers.Conv2D(8, 3, activation="relu", padding="same"),
        layers.BatchNormalization(),
        layers.MaxPooling2D(2),
        layers.Conv2D(12, 3, padding="valid", strides=2),
        layers.ReLU(),
        layers.Flatten(),
        layers.Dense(6, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    # give BN non-trivial moving stats
    m.fit(rng.standard_normal((32, 12, 12, 3)).astype(np.float32),
          np.eye(6, dtype=np.float32)[rng.integers(0, 6, 32)],
          epochs=1, verbose=0)
    save("cnn", m, rng.standard_normal((4, 12, 12, 3)).astype(np.float32))

    # 3. Sequential LSTM classifier (return_sequences False → last step)
    m = keras.Sequential([
        keras.Input((7, 5)),
        layers.LSTM(9, return_sequences=True),
        layers.LSTM(6, return_sequences=False),
        layers.Dense(3, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    save("lstm", m, rng.standard_normal((4, 7, 5)).astype(np.float32))

    # 4. Functional model with merge vertices (residual + concat)
    inp = keras.Input((10,), name="in0")
    a = layers.Dense(8, activation="relu", name="fa")(inp)
    b = layers.Dense(8, activation="tanh", name="fb")(inp)
    s = layers.Add(name="fadd")([a, b])
    c = layers.Concatenate(name="fcat")([s, a])
    out = layers.Dense(4, activation="softmax", name="fout")(c)
    m = keras.Model(inp, out)
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    save("functional", m, rng.standard_normal((6, 10)).astype(np.float32))

    # 5. MobileNet-flavored CNN: depthwise-separable stack + BN + relu6 +
    #    global pool (BASELINE config #4's MobileNet import, miniaturized)
    m = keras.Sequential([
        keras.Input((16, 16, 3)),
        layers.Conv2D(8, 3, strides=2, padding="same", use_bias=False),
        layers.BatchNormalization(),
        layers.ReLU(max_value=6.0),
        layers.DepthwiseConv2D(3, padding="same", use_bias=False),
        layers.BatchNormalization(),
        layers.ReLU(max_value=6.0),
        layers.Conv2D(16, 1, padding="same", use_bias=False),
        layers.BatchNormalization(),
        layers.ReLU(max_value=6.0),
        layers.SeparableConv2D(16, 3, padding="same"),
        layers.GlobalAveragePooling2D(),
        layers.Dense(5, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    m.fit(rng.standard_normal((32, 16, 16, 3)).astype(np.float32),
          np.eye(5, dtype=np.float32)[rng.integers(0, 5, 32)],
          epochs=1, verbose=0)
    save("mobilenet_mini", m, rng.standard_normal((4, 16, 16, 3)).astype(np.float32))

    # 6. Inception-flavored functional CNN: parallel conv towers + concat
    #    (BASELINE config #4's InceptionV3 import, miniaturized)
    inp = keras.Input((14, 14, 4), name="img")
    t1 = layers.Conv2D(6, 1, padding="same", activation="relu", name="t1c")(inp)
    t2 = layers.Conv2D(4, 1, padding="same", activation="relu", name="t2a")(inp)
    t2 = layers.Conv2D(6, 3, padding="same", activation="relu", name="t2b")(t2)
    t3 = layers.MaxPooling2D(3, strides=1, padding="same", name="t3p")(inp)
    t3 = layers.Conv2D(6, 1, padding="same", activation="relu", name="t3c")(t3)
    cat = layers.Concatenate(name="cat")([t1, t2, t3])
    bn = layers.BatchNormalization(name="bn")(cat)
    gp = layers.GlobalAveragePooling2D(name="gap")(bn)
    out = layers.Dense(3, activation="softmax", name="cls")(gp)
    m = keras.Model(inp, out)
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    m.fit(rng.standard_normal((16, 14, 14, 4)).astype(np.float32),
          np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)],
          epochs=1, verbose=0)
    save("inception_mini", m, rng.standard_normal((4, 14, 14, 4)).astype(np.float32))

    # 7. Embedding + bidirectional LSTM text classifier
    m = keras.Sequential([
        keras.Input((9,)),
        layers.Embedding(20, 6),
        layers.Bidirectional(layers.LSTM(5, return_sequences=True)),
        layers.GlobalMaxPooling1D(),
        layers.Dense(2, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    save("text_bilstm", m, rng.integers(0, 20, (4, 9)).astype(np.float32))




def gen_json_weights_pair():
    """jw_arch.json + jw.weights.h5 + jw_golden.npz — the architecture-
    JSON + weights-only pair fixture (test_architecture_json_plus_weights_pair)."""
    import numpy as np
    import keras
    from keras import layers

    keras.utils.set_random_seed(3)
    m = keras.Sequential([
        layers.Input((12,)),
        layers.Dense(8, activation="relu"),
        layers.Dense(4, activation="softmax"),
    ], name="jw")
    with open(os.path.join(OUT, "jw_arch.json"), "w") as f:
        f.write(m.to_json())
    m.save_weights(os.path.join(OUT, "jw.weights.h5"))
    rng = np.random.default_rng(4)
    x = rng.standard_normal((6, 12)).astype(np.float32)
    y = m.predict(x, verbose=0)
    np.savez(os.path.join(OUT, "jw_golden.npz"), x=x, y=y)
    print("jw pair written")


def gen_legacy_layers():
    """Fixtures for the legacy/contrib layer mappers (VERDICT r3 item 5:
    KerasLRN, KerasSpaceToDepth, KerasAtrousConvolution1D/2D). Keras 3
    has no built-in LRN/SpaceToDepth/Atrous* classes, so tiny custom
    layers with the LEGACY class names implement the reference semantics
    (tf.nn ops); the saved configs then carry exactly the class names +
    keys the old model files have, and the import path is exercised end
    to end against real TF-computed goldens."""
    import numpy as np
    import keras
    import tensorflow as tf
    from keras import layers

    @keras.saving.register_keras_serializable(package="legacy")
    class LRN(keras.layers.Layer):
        def __init__(self, alpha=1e-4, beta=0.75, k=2.0, n=5, **kw):
            super().__init__(**kw)
            self.alpha, self.beta, self.k, self.n = alpha, beta, k, int(n)

        def call(self, x):
            return tf.nn.local_response_normalization(
                x, depth_radius=self.n // 2, bias=self.k,
                alpha=self.alpha, beta=self.beta)

        def get_config(self):
            return {**super().get_config(), "alpha": self.alpha,
                    "beta": self.beta, "k": self.k, "n": self.n}

    @keras.saving.register_keras_serializable(package="legacy")
    class SpaceToDepth(keras.layers.Layer):
        def __init__(self, block_size=2, **kw):
            super().__init__(**kw)
            self.block_size = block_size

        def call(self, x):
            return tf.nn.space_to_depth(x, self.block_size)

        def get_config(self):
            return {**super().get_config(), "block_size": self.block_size}

    @keras.saving.register_keras_serializable(package="legacy")
    class AtrousConvolution2D(layers.Conv2D):
        pass

    @keras.saving.register_keras_serializable(package="legacy")
    class AtrousConvolution1D(layers.Conv1D):
        pass

    rng = np.random.default_rng(99)
    keras.utils.set_random_seed(21)

    def save(name, model, x):
        path = os.path.join(OUT, f"{name}.h5")
        model.save(path)
        y = model.predict(x, verbose=0)
        np.savez(os.path.join(OUT, f"{name}_golden.npz"), x=x, y=y)
        print(f"{name}: {path} ({os.path.getsize(path)//1024} KB), out {y.shape}")

    # AlexNet-flavored: conv → LRN → pool → dense
    m = keras.Sequential([
        keras.Input((12, 12, 3)),
        layers.Conv2D(8, 3, activation="relu", padding="same"),
        LRN(alpha=1e-3, beta=0.75, k=1.0, n=5),
        layers.MaxPooling2D(2),
        layers.Flatten(),
        layers.Dense(4, activation="softmax"),
    ])
    save("lrn", m, rng.standard_normal((4, 12, 12, 3)).astype(np.float32))

    # YOLO2-flavored reorg: conv → space-to-depth → 1x1 conv → head (the
    # flatten+dense head makes channel-ORDER errors in the reorg visible
    # in the golden while keeping the model loss-inferable)
    m = keras.Sequential([
        keras.Input((8, 8, 3)),
        layers.Conv2D(4, 3, padding="same", activation="relu"),
        SpaceToDepth(block_size=2),
        layers.Conv2D(6, 1, padding="same"),
        layers.Flatten(),
        layers.Dense(5, activation="softmax"),
    ])
    save("space_to_depth", m, rng.standard_normal((3, 8, 8, 3)).astype(np.float32))

    # dilated convs under the legacy Keras-1 class names
    m = keras.Sequential([
        keras.Input((14, 14, 3)),
        AtrousConvolution2D(6, 3, dilation_rate=2, padding="same",
                            activation="relu"),
        AtrousConvolution2D(4, 3, dilation_rate=2, padding="valid"),
        layers.GlobalAveragePooling2D(),
        layers.Dense(3, activation="softmax"),
    ])
    save("atrous2d", m, rng.standard_normal((4, 14, 14, 3)).astype(np.float32))

    m = keras.Sequential([
        keras.Input((16, 5)),
        AtrousConvolution1D(7, 3, dilation_rate=2, padding="same",
                            activation="tanh"),
        AtrousConvolution1D(4, 3, dilation_rate=3, padding="valid"),
        layers.GlobalMaxPooling1D(),
        layers.Dense(2, activation="softmax"),
    ])
    save("atrous1d", m, rng.standard_normal((4, 16, 5)).astype(np.float32))


if __name__ == "__main__":
    import sys as _sys

    if "--legacy-only" in _sys.argv:
        gen_legacy_layers()
    else:
        main()
        gen_json_weights_pair()
        gen_legacy_layers()
