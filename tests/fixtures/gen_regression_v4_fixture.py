"""Generate the round-4 serialization-regression fixture: a trained
ComputationGraph containing a FusedResNetBottleneck (the r4 layer with
multi-conv params + per-BN running stats in one layer state dict), saved
in the standard zip layout + golden outputs. COMMITTED — future rounds
must keep loading it (reference RegressionTest pattern, SURVEY §4.3).

Run once: python tests/fixtures/gen_regression_v4_fixture.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "regression")


def main():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        FusedResNetBottleneck,
        GlobalPoolingLayer,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.train.model_serializer import ModelSerializer
    from deeplearning4j_tpu.updaters import Adam

    gb = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-3))
          .weight_init("relu").graph_builder()
          .add_inputs("input")
          .set_input_types(InputType.convolutional(8, 8, 16)))
    gb.add_layer("block", FusedResNetBottleneck(width=4, project=True),
                 "input")
    gb.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "block")
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                    loss="mcxent"), "pool")
    gb.set_outputs("out")
    net = ComputationGraph(gb.build()).init()

    rng = np.random.default_rng(4)
    x = rng.standard_normal((16, 8, 8, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    net.fit(DataSet(x, y), epochs=3)

    path = os.path.join(OUT, "fused_block_adam_v4.zip")
    ModelSerializer.write_model(net, path, save_updater=True)
    out = np.asarray(net.output_single(x[:4]))
    np.savez(os.path.join(OUT, "fused_block_adam_v4_golden.npz"),
             x=x[:4], y=out, iteration=net.iteration)
    print(f"wrote {path} ({os.path.getsize(path)//1024} KB), "
          f"iteration={net.iteration}")


if __name__ == "__main__":
    main()
