"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; see ``__graft_entry__.dryrun_multichip``). Env vars must be
set before jax initializes its backends, hence the top-of-file placement.

This mirrors the reference's harness pattern of a strict base test class
(``BaseDL4JTest`` setting SCOPE_PANIC profiling,
``deeplearning4j-core/src/test/java/org/deeplearning4j/BaseDL4JTest.java:8``):
here we enable jax's strongest always-on checks instead.
"""

import os

# Force CPU: the ambient environment points JAX_PLATFORMS at the remote TPU
# ("axon"); tests must run on the virtual 8-device CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon TPU plugin overrides JAX_PLATFORMS; the config update is the
# authoritative switch to the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: repeat suite runs skip recompilation of
# unchanged jitted programs (SURVEY §4 fast-tier mandate).
#
# The cache dir is KEYED BY A HOST-CPU FINGERPRINT: XLA:CPU AOT results
# embed the compile machine's feature set, and executing an entry cached
# on a different machine can raw-SIGABRT/SIGILL ("Loading XLA:CPU AOT
# result. Target machine feature ... not supported on the host machine
# ... could lead to execution errors such as SIGILL"). Round-4 bisect:
# a 39 MB cache carried over from another host made the MoE EP+SP step
# abort on every cache hit, looking like a heisenbug in whatever test
# ran it first.


def _host_cache_tag() -> str:
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            feat = next(l for l in f if l.startswith("flags"))
    except (OSError, StopIteration):
        feat = platform.processor() or platform.machine()
    return hashlib.sha256(feat.encode()).hexdigest()[:12]


_cache_dir = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), ".jax_cache", _host_cache_tag()),
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# NaN debugging is opt-in per test (jax.debug_nans breaks some valid ops);
# keep x64 off to match TPU numerics, tests that need fp64 enable it locally.
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked @pytest.mark.slow (heavy-integration tier)",
    )


def pytest_collection_modifyitems(config, items):
    """Two-tier suite mirroring the reference's fast-unit vs
    heavy-integration split (SURVEY §4): @slow tests only run with
    --runslow or RUN_SLOW=1."""
    if config.getoption("--runslow") or os.environ.get("RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow or RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return jax.random.PRNGKey(12345)


@pytest.fixture
def np_rng():
    return np.random.default_rng(12345)
