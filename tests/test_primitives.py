"""Unit tests for core primitives: activations, initializers, losses,
schedules, updaters, regularization.

Modeled on the reference's per-subsystem behavioral unit tests
(e.g. ``nn/updater/TestUpdaters.java``, SURVEY.md §4.2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import activations, initializers, losses, schedules, updaters
from deeplearning4j_tpu.initializers import Distribution
from deeplearning4j_tpu.regularization import (
    MaxNormConstraint,
    NonNegativeConstraint,
    RegularizationConf,
    UnitNormConstraint,
    normalize_layer_gradients,
)


class TestActivations:
    def test_all_names_resolve_and_run(self):
        x = jnp.linspace(-3, 3, 13)
        for name in activations.names():
            y = activations.get(name)(x)
            assert y.shape == x.shape, name
            assert bool(jnp.all(jnp.isfinite(y))), name

    def test_known_values(self):
        x = jnp.array([-1.0, 0.0, 2.0])
        np.testing.assert_allclose(activations.get("relu")(x), [0, 0, 2])
        np.testing.assert_allclose(activations.get("hardtanh")(x), [-1, 0, 1])
        np.testing.assert_allclose(
            activations.get("sigmoid")(jnp.array([0.0])), [0.5], atol=1e-6
        )
        sm = activations.get("softmax")(jnp.array([[1.0, 1.0, 1.0]]))
        np.testing.assert_allclose(sm, [[1 / 3] * 3], atol=1e-6)

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            activations.get("nope")


class TestInitializers:
    def test_xavier_stats(self, rng):
        w = initializers.init_weights(rng, (200, 300), 200, 300, "xavier")
        assert abs(float(w.mean())) < 0.01
        expected_std = np.sqrt(2.0 / 500)
        assert abs(float(w.std()) - expected_std) < 0.005

    def test_relu_uniform_bounds(self, rng):
        w = initializers.init_weights(rng, (100, 100), 100, 100, "relu_uniform")
        lim = np.sqrt(6.0 / 100)
        assert float(w.min()) >= -lim and float(w.max()) <= lim

    def test_zero_ones_identity(self, rng):
        assert float(initializers.init_weights(rng, (3, 3), 3, 3, "zero").sum()) == 0
        assert float(initializers.init_weights(rng, (3, 3), 3, 3, "ones").sum()) == 9
        np.testing.assert_allclose(
            initializers.init_weights(rng, (3, 3), 3, 3, "identity"), np.eye(3)
        )

    def test_distribution(self, rng):
        d = Distribution("normal", mean=5.0, std=0.1)
        w = initializers.init_weights(rng, (1000,), 1, 1, "distribution", distribution=d)
        assert abs(float(w.mean()) - 5.0) < 0.05
        rt = Distribution.from_dict(d.to_dict())
        assert rt == d

    def test_orthogonal(self, rng):
        w = initializers.init_weights(rng, (16, 16), 16, 16, "orthogonal")
        np.testing.assert_allclose(w.T @ w, np.eye(16), atol=1e-2)


class TestLosses:
    def test_mcxent_matches_manual(self):
        logits = jnp.array([[2.0, 1.0, 0.1], [0.5, 2.5, -1.0]])
        labels = jnp.array([[1.0, 0, 0], [0, 1.0, 0]])
        per = losses.get("mcxent")(labels, logits, "softmax")
        p = jax.nn.softmax(logits, axis=-1)
        expected = -np.log(np.asarray(p)[[0, 1], [0, 1]])
        np.testing.assert_allclose(per, expected, rtol=1e-4)

    def test_sparse_mcxent_equals_dense(self):
        logits = jnp.array([[2.0, 1.0, 0.1], [0.5, 2.5, -1.0]])
        dense = jnp.array([[1.0, 0, 0], [0, 1.0, 0]])
        sparse = jnp.array([0, 1])
        np.testing.assert_allclose(
            losses.get("mcxent")(dense, logits, "softmax"),
            losses.get("sparse_mcxent")(sparse, logits, "softmax"),
            rtol=1e-6,
        )

    def test_xent_stable_from_logits(self):
        logits = jnp.array([[100.0, -100.0]])
        labels = jnp.array([[1.0, 0.0]])
        per = losses.get("xent")(labels, logits, "sigmoid")
        assert bool(jnp.isfinite(per).all())
        np.testing.assert_allclose(per, [0.0], atol=1e-3)

    def test_mse(self):
        out = jnp.array([[1.0, 2.0]])
        lab = jnp.array([[0.0, 0.0]])
        np.testing.assert_allclose(
            losses.get("mse")(lab, out, "identity"), [(1 + 4) / 2], rtol=1e-6
        )

    def test_mask_zeroes_contributions(self):
        logits = jnp.array([[2.0, 1.0], [3.0, -1.0]])
        labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        mask = jnp.array([[1.0], [0.0]])
        per = losses.get("mcxent")(labels, logits, "softmax", mask)
        assert float(per[1]) == 0.0 and float(per[0]) > 0.0

    def test_hinge_and_poisson_finite(self):
        y = jnp.array([[1.0, -1.0]])
        o = jnp.array([[0.3, 0.4]])
        assert float(losses.get("hinge")(y, o, "identity")[0]) > 0
        lab = jnp.array([[2.0]])
        out = jnp.array([[1.5]])
        assert np.isfinite(float(losses.get("poisson")(lab, out, "identity")[0]))


class TestSchedules:
    def test_fixed(self):
        s = schedules.FixedSchedule(0.1)
        assert float(s.value_at(0, 0)) == pytest.approx(0.1)
        assert float(s.value_at(1000, 5)) == pytest.approx(0.1)

    def test_exponential(self):
        s = schedules.ExponentialSchedule("iteration", 1.0, 0.5)
        assert float(s.value_at(3, 0)) == pytest.approx(0.125)

    def test_step(self):
        s = schedules.StepSchedule("iteration", 1.0, 0.1, 10)
        assert float(s.value_at(9, 0)) == pytest.approx(1.0)
        assert float(s.value_at(10, 0)) == pytest.approx(0.1)
        assert float(s.value_at(25, 0)) == pytest.approx(0.01, rel=1e-4)

    def test_map_schedule(self):
        s = schedules.MapSchedule("epoch", {0: 0.1, 5: 0.01, 10: 0.001})
        assert float(s.value_at(0, 0)) == pytest.approx(0.1)
        assert float(s.value_at(0, 4)) == pytest.approx(0.1)
        assert float(s.value_at(0, 5)) == pytest.approx(0.01)
        assert float(s.value_at(0, 99)) == pytest.approx(0.001)

    def test_poly(self):
        s = schedules.PolySchedule("iteration", 1.0, 2.0, 100)
        assert float(s.value_at(0, 0)) == pytest.approx(1.0)
        assert float(s.value_at(50, 0)) == pytest.approx(0.25)
        assert float(s.value_at(100, 0)) == pytest.approx(0.0)

    def test_cosine(self):
        s = schedules.CosineSchedule(1.0, decay_steps=100, final=0.1)
        assert float(s.value_at(0, 0)) == pytest.approx(1.0)
        assert float(s.value_at(50, 0)) == pytest.approx(0.55, abs=1e-6)
        assert float(s.value_at(100, 0)) == pytest.approx(0.1)
        assert float(s.value_at(500, 0)) == pytest.approx(0.1)  # holds final

    def test_warmup_wraps_any_schedule(self):
        s = schedules.WarmupSchedule(10, schedules.CosineSchedule(
            1.0, decay_steps=100, final=0.0))
        assert float(s.value_at(0, 0)) == pytest.approx(0.0)
        assert float(s.value_at(5, 0)) == pytest.approx(0.5)
        assert float(s.value_at(10, 0)) == pytest.approx(1.0)
        # post-warmup: cosine evaluated with the warmup offset removed
        assert float(s.value_at(60, 0)) == pytest.approx(0.5)
        # plain-float base
        w = schedules.WarmupSchedule(4, 0.2)
        assert float(w.value_at(2, 0)) == pytest.approx(0.1)
        assert float(w.value_at(100, 0)) == pytest.approx(0.2)

    def test_warmup_cosine_drives_updater(self):
        from deeplearning4j_tpu.updaters import Sgd

        upd = Sgd(schedules.WarmupSchedule(5, 1.0))
        assert float(upd.lr(0, 0)) == pytest.approx(0.0)
        assert float(upd.lr(5, 0)) == pytest.approx(1.0)

    def test_serde_roundtrip(self):
        for s in [
            schedules.FixedSchedule(0.3),
            schedules.ExponentialSchedule("epoch", 1.0, 0.9),
            schedules.MapSchedule("iteration", {0: 1.0, 3: 0.5}),
            schedules.StepSchedule("iteration", 1.0, 0.5, 7),
            schedules.CosineSchedule(1.0, 50, 0.05),
            schedules.WarmupSchedule(8, schedules.CosineSchedule(1.0, 50)),
        ]:
            rt = schedules.Schedule.from_dict(s.to_dict())
            assert rt == s

    def test_traceable_under_jit(self):
        s = schedules.StepSchedule("iteration", 1.0, 0.1, 10)

        @jax.jit
        def f(it):
            return s.value_at(it, jnp.asarray(0))

        assert float(f(jnp.asarray(15))) == pytest.approx(0.1)


def _run_updater(u, grad, steps=3, param_shape=None):
    param_shape = param_shape or grad.shape
    state = u.init_state(jnp.zeros(param_shape))
    upd = None
    for t in range(1, steps + 1):
        upd, state = u.apply(grad, state, jnp.asarray(t), jnp.asarray(t - 1), jnp.asarray(0))
    return upd, state


class TestUpdaters:
    def test_sgd(self):
        g = jnp.array([1.0, -2.0])
        upd, _ = _run_updater(updaters.Sgd(0.5), g, steps=1)
        np.testing.assert_allclose(upd, [0.5, -1.0])

    def test_adam_first_step_magnitude(self):
        # After one Adam step, update ≈ lr * sign(g) (bias-corrected).
        g = jnp.array([0.3, -0.7, 1.5])
        upd, _ = _run_updater(updaters.Adam(0.001), g, steps=1)
        np.testing.assert_allclose(jnp.abs(upd), [0.001] * 3, rtol=1e-3)
        np.testing.assert_allclose(jnp.sign(upd), jnp.sign(g))

    def test_nesterov_momentum_accumulates(self):
        g = jnp.array([1.0])
        u1, _ = _run_updater(updaters.Nesterovs(0.1, momentum=0.9), g, steps=1)
        u5, _ = _run_updater(updaters.Nesterovs(0.1, momentum=0.9), g, steps=5)
        assert float(u5[0]) > float(u1[0]) > 0

    def test_adagrad_decreases_effective_lr(self):
        g = jnp.array([1.0])
        u1, _ = _run_updater(updaters.AdaGrad(0.1), g, steps=1)
        u10, _ = _run_updater(updaters.AdaGrad(0.1), g, steps=10)
        assert float(u10[0]) < float(u1[0])

    def test_adadelta_no_lr(self):
        u = updaters.AdaDelta()
        assert not u.has_learning_rate
        g = jnp.array([0.5])
        upd, st = _run_updater(u, g, steps=2)
        assert np.isfinite(float(upd[0]))
        assert set(st) == {"msg", "msdx"}

    def test_noop_passthrough(self):
        g = jnp.array([3.0])
        upd, _ = _run_updater(updaters.NoOp(), g, steps=1)
        np.testing.assert_allclose(upd, g)

    def test_all_updaters_descend_quadratic(self):
        # Minimise f(x) = x² from x=5 — every updater must reduce |x|.
        for name in ["sgd", "adam", "adamax", "nadam", "amsgrad", "adagrad",
                     "adadelta", "rmsprop", "nesterovs"]:
            u = updaters.get(name)
            x = jnp.array([5.0])
            state = u.init_state(x)
            for t in range(1, 201):
                grad = 2 * x
                upd, state = u.apply(grad, state, jnp.asarray(t), jnp.asarray(t - 1), jnp.asarray(0))
                x = x - upd
            assert abs(float(x[0])) < 5.0, name

    def test_serde_roundtrip(self):
        for u in [
            updaters.Adam(0.01, beta1=0.8),
            updaters.Nesterovs(0.1, momentum=schedules.StepSchedule("epoch", 0.9, 0.99, 2)),
            updaters.AdaDelta(rho=0.9),
            updaters.Sgd(schedules.ExponentialSchedule("iteration", 0.1, 0.999)),
        ]:
            rt = updaters.Updater.from_dict(u.to_dict())
            assert rt == u

    def test_lr_schedule_inside_updater(self):
        u = updaters.Sgd(schedules.StepSchedule("iteration", 1.0, 0.1, 10))
        g = jnp.array([1.0])
        upd0, _ = u.apply(g, {}, jnp.asarray(1), jnp.asarray(0), jnp.asarray(0))
        upd15, _ = u.apply(g, {}, jnp.asarray(16), jnp.asarray(15), jnp.asarray(0))
        assert float(upd0[0]) == pytest.approx(1.0)
        assert float(upd15[0]) == pytest.approx(0.1)


class TestRegularization:
    def test_l2_grad_term(self):
        r = RegularizationConf(l2=0.1)
        p = jnp.array([2.0, -4.0])
        np.testing.assert_allclose(r.grad_term("W", p), [0.2, -0.4], rtol=1e-6)
        assert r.grad_term("b", p) is None

    def test_l1_score(self):
        r = RegularizationConf(l1=0.5)
        p = jnp.array([1.0, -3.0])
        assert float(r.score_term("W", p)) == pytest.approx(2.0)

    def test_clip_elementwise(self):
        g = {"W": jnp.array([5.0, -0.5])}
        out = normalize_layer_gradients(g, "clip_element_wise_absolute_value", 1.0)
        np.testing.assert_allclose(out["W"], [1.0, -0.5])

    def test_clip_l2_per_layer(self):
        g = {"W": jnp.array([3.0, 4.0])}  # norm 5
        out = normalize_layer_gradients(g, "clip_l2_per_layer", 1.0)
        np.testing.assert_allclose(
            np.sqrt(np.sum(np.asarray(out["W"]) ** 2)), 1.0, rtol=1e-4
        )
        # below threshold: unchanged
        g2 = {"W": jnp.array([0.3, 0.4])}
        out2 = normalize_layer_gradients(g2, "clip_l2_per_layer", 1.0)
        np.testing.assert_allclose(out2["W"], g2["W"], rtol=1e-5)

    def test_renormalize_per_param_type(self):
        g = {"W": jnp.array([3.0, 4.0]), "b": jnp.array([0.0, 2.0])}
        out = normalize_layer_gradients(g, "renormalize_l2_per_param_type")
        np.testing.assert_allclose(np.linalg.norm(out["W"]), 1.0, rtol=1e-4)
        np.testing.assert_allclose(np.linalg.norm(out["b"]), 1.0, rtol=1e-4)

    def test_constraints(self):
        w = jnp.array([[3.0, 0.1], [4.0, 0.1]])  # col norms: 5, ~0.14
        c = MaxNormConstraint(1.0)
        out = c.apply(w)
        norms = np.linalg.norm(np.asarray(out), axis=0)
        assert norms[0] == pytest.approx(1.0, rel=1e-4)
        assert norms[1] == pytest.approx(np.linalg.norm([0.1, 0.1]), rel=1e-3)
        np.testing.assert_allclose(
            NonNegativeConstraint().apply(jnp.array([-1.0, 2.0])), [0.0, 2.0]
        )
        u = UnitNormConstraint().apply(w)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(u), axis=0), [1, 1], rtol=1e-4)


class TestPoolingAliases:
    def test_pooling_aliases_are_subsampling(self):
        """reference Pooling1D/Pooling2D are empty subclasses of the
        Subsampling layers (Pooling2D.java) — same here, serde-resolvable
        under the alias names."""
        from deeplearning4j_tpu.nn.conf import serde
        from deeplearning4j_tpu.nn.conf.layers import (
            Pooling1D,
            Pooling2D,
            Subsampling1DLayer,
            SubsamplingLayer,
        )

        assert issubclass(Pooling2D, SubsamplingLayer)
        assert issubclass(Pooling1D, Subsampling1DLayer)
        p2 = serde.decode(serde.encode(Pooling2D(kernel_size=(3, 3))))
        assert type(p2) is Pooling2D and list(p2.kernel_size) == [3, 3]
        p1 = serde.decode(serde.encode(Pooling1D(kernel_size=4)))
        assert type(p1) is Pooling1D and p1.kernel_size == 4


class TestAuxPreprocessors:
    def test_normalizing_and_composable_preprocessors(self):
        """reference preprocessor tail: ZeroMean / UnitVariance /
        ZeroMeanAndUnitVariance / Composable / BinomialSampling."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf import serde
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            BinomialSamplingPreProcessor,
            ComposableInputPreProcessor,
            UnitVarianceProcessor,
            ZeroMeanAndUnitVariancePreProcessor,
            ZeroMeanPrePreProcessor,
        )

        x = jnp.asarray(
            np.random.default_rng(0).random((8, 5)).astype(np.float32))
        z = ZeroMeanAndUnitVariancePreProcessor().pre_process(x)
        np.testing.assert_allclose(np.asarray(z).mean(0), 0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(z).std(0), 1, atol=1e-3)
        comp = ComposableInputPreProcessor(
            ZeroMeanPrePreProcessor(), UnitVarianceProcessor())
        np.testing.assert_allclose(np.asarray(comp.pre_process(x)),
                                   np.asarray(z), atol=1e-5)
        assert comp.get_output_type(InputType.feed_forward(5)).size == 5
        b = BinomialSamplingPreProcessor(seed=3).pre_process(x)
        assert set(np.unique(np.asarray(b))) <= {0.0, 1.0}
        rt = serde.decode(serde.encode(comp))
        assert type(rt) is ComposableInputPreProcessor
        assert len(rt.preprocessors) == 2
