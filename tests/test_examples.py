"""Every example under examples/ must run to completion (reference
pattern: dl4j-examples are the de-facto integration suite users copy
from — a broken example is a broken onboarding path).

Each runs in a subprocess on the 8-device virtual CPU mesh, exactly as
the examples' own docstrings instruct."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py") and not f.startswith("_")
)


@pytest.mark.slow
@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, (
        f"{script} failed\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}"
    )
    assert "OK" in proc.stdout


def test_examples_inventory_matches_readme():
    readme = open(os.path.join(REPO, "examples", "README.md")).read()
    for f in EXAMPLES:
        assert f in readme, f"examples/README.md does not list {f}"
