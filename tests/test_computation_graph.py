"""ComputationGraph tests.

Mirrors the reference suites ``nn/graph/TestComputationGraphNetwork.java``
(behavioral) and ``gradientcheck/GradientCheckTestsComputationGraph.java``
(numerical backbone).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_builder import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    MergeVertex,
    ReshapeVertex,
    ReverseTimeSeriesVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.gradient_check import check_gradients_graph
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _simple_graph(seed=12345):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater("sgd")
        .graph_builder()
        .add_inputs("in")
        .add_layer("d0", DenseLayer(n_out=8, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "d0")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )
    return ComputationGraph(conf).init()


def _iris_like(n=60, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


class TestBasics:
    def test_fit_reduces_score(self):
        net = _simple_graph()
        ds = _iris_like()
        s0 = net.score(ds)
        net.fit(ListDataSetIterator(ds, 16), epochs=20)
        assert net.score(ds) < s0

    def test_output_shape(self):
        net = _simple_graph()
        y = net.output_single(np.zeros((5, 4), np.float32))
        assert y.shape == (5, 3)
        np.testing.assert_allclose(y.sum(axis=1), 1.0, rtol=1e-5)

    def test_serde_roundtrip(self):
        net = _simple_graph()
        js = net.conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(js)
        assert conf2 == net.conf
        net2 = ComputationGraph(conf2).init()
        assert net2.num_params() == net.num_params()

    def test_clone_and_params_flat(self):
        net = _simple_graph()
        ds = _iris_like()
        net.fit(ds, batch_size=16)
        c = net.clone()
        np.testing.assert_array_equal(c.params_flat(), net.params_flat())
        x = np.random.default_rng(1).standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(c.output_single(x), net.output_single(x), rtol=1e-6)

    def test_params_flat_roundtrip(self):
        net = _simple_graph()
        vec = net.params_flat()
        net2 = _simple_graph(seed=999)
        net2.set_params_flat(vec)
        np.testing.assert_array_equal(net2.params_flat(), vec)

    def test_mln_parity(self):
        """Same layers as a graph and as an MLN with identical params give
        identical outputs (reference testConfigurationBasic-style parity)."""
        mln_conf = (
            NeuralNetConfiguration.builder().seed(12345).updater("sgd").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        mln = MultiLayerNetwork(mln_conf).init()
        cg = _simple_graph()
        cg.set_params_flat(mln.params_flat())
        x = np.random.default_rng(2).standard_normal((7, 4)).astype(np.float32)
        np.testing.assert_allclose(cg.output_single(x), mln.output(x), rtol=1e-5)


class TestMultiInputOutput:
    def _two_in_two_out(self):
        return (
            NeuralNetConfiguration.builder().seed(1).updater("sgd")
            .graph_builder()
            .add_inputs("inA", "inB")
            .add_layer("dA", DenseLayer(n_out=6, activation="relu"), "inA")
            .add_layer("dB", DenseLayer(n_out=6, activation="relu"), "inB")
            .add_vertex("merge", MergeVertex(), "dA", "dB")
            .add_layer("outA", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "merge")
            .add_layer("outB", OutputLayer(n_out=1, activation="identity", loss="mse"), "merge")
            .set_outputs("outA", "outB")
            .set_input_types(InputType.feed_forward(3), InputType.feed_forward(5))
            .build()
        )

    def test_merge_shapes(self):
        net = ComputationGraph(self._two_in_two_out()).init()
        rng = np.random.default_rng(0)
        a = rng.standard_normal((4, 3)).astype(np.float32)
        b = rng.standard_normal((4, 5)).astype(np.float32)
        ya, yb = net.output(a, b)
        assert ya.shape == (4, 2)
        assert yb.shape == (4, 1)

    def test_fit_multidataset(self):
        net = ComputationGraph(self._two_in_two_out()).init()
        rng = np.random.default_rng(0)
        n = 32
        mds = MultiDataSet(
            [rng.standard_normal((n, 3)).astype(np.float32),
             rng.standard_normal((n, 5)).astype(np.float32)],
            [np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)],
             rng.standard_normal((n, 1)).astype(np.float32)],
        )
        s0 = net.score(mds)
        for _ in range(30):
            net.fit(mds)
        assert net.score(mds) < s0

    def test_gradients_multi(self):
        net = ComputationGraph(self._two_in_two_out()).init()
        rng = np.random.default_rng(3)
        n = 4
        mds = MultiDataSet(
            [rng.standard_normal((n, 3)), rng.standard_normal((n, 5))],
            [np.eye(2)[rng.integers(0, 2, n)], rng.standard_normal((n, 1))],
        )
        assert check_gradients_graph(net, mds, print_results=True)


class TestVertices:
    def test_elementwise_ops(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((3, 4)).astype(np.float32)
        b = rng.standard_normal((3, 4)).astype(np.float32)
        import jax.numpy as jnp

        cases = {
            "add": a + b, "subtract": a - b, "product": a * b,
            "average": (a + b) / 2, "max": np.maximum(a, b),
        }
        for op, want in cases.items():
            got = ElementWiseVertex(op).apply([jnp.asarray(a), jnp.asarray(b)], [None, None])
            np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, err_msg=op)

    def test_residual_add_graph(self):
        """Skip connection: the shape every ResNet block needs."""
        conf = (
            NeuralNetConfiguration.builder().seed(5).updater("sgd")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_out=4, activation="relu"), "in")
            .add_vertex("res", ElementWiseVertex("add"), "d1", "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "res")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        ds = DataSet(rng.standard_normal((4, 4)), np.eye(2)[rng.integers(0, 2, 4)])
        assert check_gradients_graph(net, ds, print_results=True)

    def test_subset_scale_shift(self):
        import jax.numpy as jnp

        x = jnp.arange(12.0).reshape(2, 6)
        got = SubsetVertex(1, 3).apply([x], [None])
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x)[:, 1:4])
        np.testing.assert_allclose(np.asarray(ScaleVertex(2.0).apply([x], [None])), np.asarray(x) * 2)
        np.testing.assert_allclose(np.asarray(ShiftVertex(1.5).apply([x], [None])), np.asarray(x) + 1.5)

    def test_stack_unstack(self):
        import jax.numpy as jnp

        a = jnp.ones((2, 3))
        b = jnp.zeros((2, 3))
        s = StackVertex().apply([a, b], [None, None])
        assert s.shape == (4, 3)
        u0 = UnstackVertex(0, 2).apply([s], [None])
        u1 = UnstackVertex(1, 2).apply([s], [None])
        np.testing.assert_array_equal(np.asarray(u0), np.asarray(a))
        np.testing.assert_array_equal(np.asarray(u1), np.asarray(b))

    def test_l2_vertices(self):
        import jax.numpy as jnp

        a = jnp.asarray([[3.0, 4.0]])
        b = jnp.zeros((1, 2))
        d = L2Vertex(eps=0.0).apply([a, b], [None, None])
        np.testing.assert_allclose(np.asarray(d), [[5.0]], rtol=1e-6)
        n = L2NormalizeVertex(eps=0.0).apply([a], [None])
        np.testing.assert_allclose(np.asarray(n), [[0.6, 0.8]], rtol=1e-6)

    def test_reshape_vertex(self):
        import jax.numpy as jnp

        x = jnp.arange(24.0).reshape(2, 12)
        y = ReshapeVertex([-1, 3, 4]).apply([x], [None])
        assert y.shape == (2, 3, 4)

    def test_pool_helper_vertex(self):
        """reference PoolHelperVertex.doForward: strip the first spatial
        row+column (NHWC here; NCHW [:, :, 1:, 1:] there)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.graph_vertices import (
            PoolHelperVertex,
        )
        from deeplearning4j_tpu.nn.conf.input_type import InputType

        v = PoolHelperVertex()
        ot = v.get_output_type(InputType.convolutional(8, 8, 3))
        assert (ot.height, ot.width, ot.channels) == (7, 7, 3)
        x = jnp.arange(2.0 * 8 * 8 * 3).reshape(2, 8, 8, 3)
        y = v.apply([x], [None])
        assert y.shape == (2, 7, 7, 3)
        np.testing.assert_array_equal(np.asarray(y),
                                      np.asarray(x)[:, 1:, 1:, :])

    def test_reverse_timeseries_masked(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.arange(8.0).reshape(1, 4, 2))
        m = jnp.asarray([[1.0, 1.0, 1.0, 0.0]])
        y = np.asarray(ReverseTimeSeriesVertex().apply([x], [m]))
        # valid prefix [t0,t1,t2] reversed; padded step t3 untouched
        np.testing.assert_array_equal(y[0, 0], [4.0, 5.0])
        np.testing.assert_array_equal(y[0, 2], [0.0, 1.0])
        np.testing.assert_array_equal(y[0, 3], [6.0, 7.0])

    def test_last_time_step_masked(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.arange(12.0).reshape(1, 6, 2))
        m = jnp.asarray([[1.0, 1.0, 1.0, 1.0, 0.0, 0.0]])
        y = np.asarray(LastTimeStepVertex().apply([x], [m]))
        np.testing.assert_array_equal(y, [[6.0, 7.0]])


class TestRnnGraph:
    def test_seq2class_graph(self):
        """LSTM encoder → LastTimeStep vertex → classifier; masked."""
        conf = (
            NeuralNetConfiguration.builder().seed(7).updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", LSTM(n_out=8, activation="tanh"), "in")
            .add_vertex("last", LastTimeStepVertex("in"), "lstm")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "last")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(3))
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        n, T = 16, 7
        x = rng.standard_normal((n, T, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
        mask = (np.arange(T)[None, :] < rng.integers(3, T + 1, n)[:, None]).astype(np.float32)
        ds = DataSet(x, y, features_mask=mask)
        s0 = net.score(ds)
        net.fit(ListDataSetIterator(ds, 8), epochs=10)
        assert net.score(ds) < s0
        out = net.output_single(x, masks=[mask])
        assert out.shape == (n, 2)

    def test_duplicate_to_timeseries(self):
        """Encoder-decoder shape: static vector broadcast over time."""
        conf = (
            NeuralNetConfiguration.builder().seed(7).updater("sgd")
            .graph_builder()
            .add_inputs("seq", "static")
            .add_layer("dstatic", DenseLayer(n_out=4, activation="tanh"), "static")
            .add_vertex("dup", DuplicateToTimeSeriesVertex("seq"), "dstatic", "seq")
            .add_vertex("merge", MergeVertex(), "seq", "dup")
            .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"), "merge")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(3), InputType.feed_forward(5))
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        n, T = 4, 5
        mds = MultiDataSet(
            [rng.standard_normal((n, T, 3)).astype(np.float32),
             rng.standard_normal((n, 5)).astype(np.float32)],
            [np.eye(2, dtype=np.float32)[rng.integers(0, 2, (n, T))]],
        )
        ys = net.output(mds.features[0], mds.features[1])
        assert ys[0].shape == (n, T, 2)
        assert check_gradients_graph(net, mds, print_results=True)


class TestGraphGradients:
    def test_simple_graph_gradients(self):
        net = _simple_graph()
        ds = _iris_like(n=5, seed=3)
        assert check_gradients_graph(net, ds, print_results=True)

    def test_cycle_detection(self):
        with pytest.raises(ValueError, match="cycle"):
            (
                NeuralNetConfiguration.builder().graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=4), "in", "b")
                .add_layer("b", DenseLayer(n_out=4), "a")
                .add_layer("out", OutputLayer(n_out=2), "b")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build()
            )

    def test_unknown_input_rejected(self):
        with pytest.raises(ValueError, match="does not exist"):
            (
                NeuralNetConfiguration.builder().graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=4), "nope")
                .set_outputs("a")
                .build()
            )


class TestGraphSerialization:
    def test_checkpoint_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.train.model_serializer import (
            ModelGuesser,
            ModelSerializer,
        )

        net = _simple_graph()
        ds = _iris_like()
        net.fit(ds, batch_size=16)
        p = str(tmp_path / "graph.zip")
        ModelSerializer.write_model(net, p)
        net2 = ModelSerializer.restore_computation_graph(p)
        np.testing.assert_array_equal(net2.params_flat(), net.params_flat())
        np.testing.assert_array_equal(net2.opt_state_flat(), net.opt_state_flat())
        assert net2.iteration == net.iteration
        x = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(net2.output_single(x), net.output_single(x), rtol=1e-6)
        # guesser dispatches on meta model_type
        net3 = ModelGuesser.load_model_guess(p)
        np.testing.assert_array_equal(net3.params_flat(), net.params_flat())


class TestGraphParallel:
    def test_graph_under_parallel_wrapper(self):
        """ComputationGraph + data-parallel wrapper on the 8-device mesh."""
        from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        net = _simple_graph()
        ds = _iris_like(n=24)
        pw = ParallelWrapper(net)
        s_before = net.score(ds)
        pw.fit(ExistingDataSetIterator(ds.batch_by(24)), epochs=15)
        assert net.iteration == 15
        assert net.score(ds) < s_before

    def test_duplicate_vertex_reference_style(self):
        """Constructor-arg-only usage (reference API): timestep source is
        auto-wired as a graph edge."""
        conf = (
            NeuralNetConfiguration.builder().seed(7).updater("sgd")
            .graph_builder()
            .add_inputs("seq", "static")
            .add_layer("dstatic", DenseLayer(n_out=4, activation="tanh"), "static")
            .add_vertex("dup", DuplicateToTimeSeriesVertex("seq"), "dstatic")
            .add_vertex("merge", MergeVertex(), "seq", "dup")
            .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"), "merge")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(3), InputType.feed_forward(5))
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        n, T = 3, 4
        ys = net.output(
            rng.standard_normal((n, T, 3)).astype(np.float32),
            rng.standard_normal((n, 5)).astype(np.float32),
        )
        assert ys[0].shape == (n, T, 2)

    def test_non_output_layer_output_rejected(self):
        conf = (
            NeuralNetConfiguration.builder().graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=4), "in")
            .set_outputs("d")
            .set_input_types(InputType.feed_forward(4))
            .build()
        )
        with pytest.raises(ValueError, match="not an output layer"):
            ComputationGraph(conf)


class TestGraphSerdeOrdering:
    def test_topo_order_survives_json_roundtrip(self):
        """Non-alphabetical parallel branches: flattened-param order must be
        identical after a JSON round-trip (regression: sort_keys used to
        reorder vertex insertion order and corrupt restored params)."""
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,
        )

        gb = (
            NeuralNetConfiguration.builder().seed(1).graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("z1", DenseLayer(n_out=5, activation="relu"), "in")
            .add_layer("a2", DenseLayer(n_out=5, activation="relu"), "in")
            .add_vertex("merge", MergeVertex(), "z1", "a2")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "merge")
            .set_outputs("out")
        )
        conf = gb.build()
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert conf2.topological_order == conf.topological_order
        net = ComputationGraph(conf).init()
        net2 = ComputationGraph(conf2).init()
        assert net2.layer_names == net.layer_names

    def test_multi_input_layer_auto_merges(self):
        """A layer declared with two inputs gets an implicit MergeVertex
        (reference GraphBuilder behavior) instead of silently dropping
        the second input."""
        import numpy as np

        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        gb = (
            NeuralNetConfiguration.builder().seed(1).graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("dA", DenseLayer(n_out=3, activation="relu"), "in")
            .add_layer("dB", DenseLayer(n_out=5, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
                       "dA", "dB")
            .set_outputs("out")
        )
        net = ComputationGraph(gb.build()).init()
        # out's weight matrix must see merged width 3+5=8
        assert net.params_["out"]["W"].shape == (8, 2)
        y = net.output_single(np.zeros((2, 4), np.float32))
        assert y.shape == (2, 2)

    def test_unstack_indivisible_batch_raises(self):
        import jax.numpy as jnp
        import pytest

        from deeplearning4j_tpu.nn.conf.graph_vertices import UnstackVertex

        v = UnstackVertex(from_idx=0, stack_size=2)
        with pytest.raises(ValueError, match="not divisible"):
            v.apply([jnp.zeros((5, 3))], [None])


class TestGraphSummary:
    def test_summary_table(self):
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (NeuralNetConfiguration.builder().seed(1).graph_builder()
                .add_inputs("a", "b")
                .add_layer("d1", DenseLayer(n_out=4, activation="relu"), "a")
                .add_layer("d2", DenseLayer(n_out=4, activation="relu"), "b")
                .add_vertex("m", MergeVertex(), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(3)).build())
        cg = ComputationGraph(conf).init()
        s = cg.summary()
        assert "NetworkInput" in s and "MergeVertex" in s
        assert f"Total parameters: {cg.num_params():,}" in s
