"""Sklearn-style estimator adapters (the dl4j-spark-ml analog) and
PoS-filtered tokenization (the nlp-uima capability analog)."""

import numpy as np
import pytest

from deeplearning4j_tpu.estimator import (
    NeuralNetClassifier,
    NeuralNetRegressor,
)
from deeplearning4j_tpu.nlp.tokenization_plugins import (
    PosFilterTokenizerFactory,
    pos_tag,
)


def _clf_conf(n_in=4, n_classes=3):
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.updaters import Adam

    return (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _blobs(n=240, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.asarray([[2, 0, 0, 0], [0, 2, 0, 0], [0, 0, 2, 0]],
                         np.float32)
    y = rng.integers(0, 3, n)
    X = centers[y] + 0.3 * rng.normal(size=(n, 4)).astype(np.float32)
    return X, y


class TestNeuralNetClassifier:
    def test_fit_predict_score(self):
        X, y = _blobs()
        clf = NeuralNetClassifier(_clf_conf, epochs=20, batch_size=32)
        clf.fit(X, y)
        assert clf.score(X, y) > 0.9
        proba = clf.predict_proba(X[:5])
        assert proba.shape == (5, 3)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)

    def test_string_labels(self):
        X, y = _blobs()
        names = np.asarray(["cat", "dog", "fish"])[y]
        clf = NeuralNetClassifier(_clf_conf, epochs=15).fit(X, names)
        assert set(clf.predict(X[:20])) <= {"cat", "dog", "fish"}
        assert list(clf.classes_) == ["cat", "dog", "fish"]

    def test_partial_fit_requires_classes_then_learns(self):
        X, y = _blobs()
        clf = NeuralNetClassifier(_clf_conf, batch_size=32)
        with pytest.raises(ValueError, match="classes"):
            clf.partial_fit(X, y)
        clf.partial_fit(X, y, classes=[0, 1, 2])
        for _ in range(15):
            clf.partial_fit(X, y)
        assert clf.score(X, y) > 0.9

    def test_get_set_params_protocol(self):
        clf = NeuralNetClassifier(_clf_conf, epochs=3)
        p = clf.get_params()
        assert p["epochs"] == 3
        clf.set_params(epochs=5, batch_size=8)
        assert clf.epochs == 5 and clf.batch_size == 8
        with pytest.raises(ValueError, match="Invalid parameter"):
            clf.set_params(bogus=1)

    def test_unfitted_predict_raises(self):
        with pytest.raises(RuntimeError, match="not fitted"):
            NeuralNetClassifier(_clf_conf).predict(np.zeros((1, 4)))

    def test_sklearn_pipeline_compat_if_available(self):
        sklearn = pytest.importorskip("sklearn")
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        X, y = _blobs()
        pipe = Pipeline([
            ("scale", StandardScaler()),
            ("net", NeuralNetClassifier(_clf_conf, epochs=15)),
        ])
        pipe.fit(X, y)
        assert pipe.score(X, y) > 0.9


class TestConfFactoryDeepParams:
    """conf-factory hyperparameters surface as conf__<name> deep params,
    so sklearn clone/GridSearchCV and the tuner bridge can search the
    NETWORK's hyperparameters."""

    def _factory(self, **hyper):
        import functools

        from deeplearning4j_tpu.tune import ConfFactory, mlp_factory

        return ConfFactory(functools.partial(mlp_factory, 4, 3),
                           widths=(16,), **hyper)

    def test_get_params_deep_exposes_factory_hypers(self):
        clf = NeuralNetClassifier(self._factory(lr=1e-2, l2=1e-4),
                                  epochs=3)
        deep = clf.get_params(deep=True)
        assert deep["conf__lr"] == 1e-2 and deep["conf__l2"] == 1e-4
        assert "conf__lr" not in clf.get_params(deep=False)

    def test_set_params_routes_conf_and_copies_on_write(self):
        factory = self._factory(lr=1e-2)
        a = NeuralNetClassifier(factory, epochs=2)
        # sklearn.clone semantics: the clone receives the SAME factory
        b = NeuralNetClassifier(**{k: v for k, v in
                                   a.get_params(deep=False).items()})
        b.set_params(conf__lr=5e-3)
        assert b.get_params()["conf__lr"] == 5e-3
        # a's factory must be untouched (grid points are independent)
        assert a.get_params()["conf__lr"] == 1e-2
        assert factory.get_params()["lr"] == 1e-2
        with pytest.raises(ValueError, match="with_params"):
            NeuralNetClassifier(_clf_conf).set_params(conf__lr=1e-3)

    def test_fit_uses_routed_hyperparameters(self):
        X, y = _blobs()
        clf = NeuralNetClassifier(self._factory(), epochs=12,
                                  batch_size=32)
        clf.set_params(conf__lr=1e-2)
        clf.fit(X, y)
        assert clf.score(X, y) > 0.9
        lr = clf.net_.layers[0].updater.fixed_learning_rate()
        assert lr == pytest.approx(1e-2)

    def test_gridsearchcv_over_conf_params_if_available(self):
        pytest.importorskip("sklearn")
        from sklearn.model_selection import GridSearchCV

        X, y = _blobs(n=120)
        gs = GridSearchCV(
            NeuralNetClassifier(self._factory(), epochs=6, batch_size=32),
            {"conf__lr": [1e-2, 1e-3]}, cv=2)
        gs.fit(X, y)
        assert gs.best_params_["conf__lr"] in (1e-2, 1e-3)

    def test_estimator_tuner_bridge_smoke(self):
        """A search space over an estimator: sampled conf__/loop params
        route through set_params, trials score on a held-out split."""
        from deeplearning4j_tpu.tune import (
            ContinuousParameterSpace,
            search_estimator,
        )

        X, y = _blobs(n=160)
        out = search_estimator(
            NeuralNetClassifier(self._factory(), epochs=4, batch_size=32),
            {"conf__lr": ContinuousParameterSpace(1e-3, 3e-2,
                                                  scale="log")},
            X, y, num_trials=3, seed=5)
        assert len(out["results"]) == 3
        assert out["best_params"] in [r["params"] for r in out["results"]]
        assert out["best_score"] == max(r["score"] for r in out["results"])


class TestNeuralNetRegressor:
    def test_fit_and_r2(self):
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.updaters import Adam

        def conf():
            return (NeuralNetConfiguration.builder().seed(1)
                    .updater(Adam(1e-2)).list()
                    .layer(DenseLayer(n_out=16, activation="tanh"))
                    .layer(OutputLayer(n_out=1, activation="identity",
                                       loss="mse"))
                    .set_input_type(InputType.feed_forward(3)).build())

        rng = np.random.default_rng(0)
        X = rng.normal(size=(256, 3)).astype(np.float32)
        y = (X @ np.asarray([1.0, -2.0, 0.5], np.float32)
             + 0.05 * rng.normal(size=256).astype(np.float32))
        reg = NeuralNetRegressor(conf, epochs=40, batch_size=64)
        reg.fit(X, y)
        assert reg.score(X, y) > 0.95
        assert reg.predict(X[:7]).shape == (7,)


class TestPosFilteredTokenization:
    def test_tagger_closed_class_and_suffixes(self):
        assert pos_tag("the") == "DT"
        assert pos_tag("with") == "IN"
        assert pos_tag("quickly") == "RB"
        assert pos_tag("running") == "VBG"
        assert pos_tag("movement") == "NN"
        assert pos_tag("beautiful") == "JJ"
        assert pos_tag("42") == "CD"
        assert pos_tag("London") == "NNP"
        assert pos_tag("dogs") == "NNS"

    def test_filter_replaces_disallowed_with_none(self):
        """reference PosUimaTokenizer: invalid tokens become the literal
        "NONE" so window positions are preserved."""
        tf = PosFilterTokenizerFactory(["NN", "JJ"])
        toks = tf.create("the beautiful movement ran quickly").get_tokens()
        assert toks == ["NONE", "beautiful", "movement", "NONE", "NONE"]

    def test_strip_nones_drops_them(self):
        tf = PosFilterTokenizerFactory(["NN"], strip_nones=True)
        toks = tf.create("the movement of the nation").get_tokens()
        assert toks == ["movement", "nation"]

    def test_group_prefix_matching(self):
        """an allowed "VB" admits the whole verb group."""
        tf = PosFilterTokenizerFactory(["VB"], strip_nones=True)
        toks = tf.create("she was running and jumped").get_tokens()
        assert toks == ["was", "running", "jumped"]

    def test_feeds_word2vec_vocab(self):
        """end-to-end: PoS-filtered factory plugs into the Word2Vec
        tokenization SPI like any other TokenizerFactory."""
        from deeplearning4j_tpu.nlp.sentence_iterator import (
            CollectionSentenceIterator,
        )
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        sents = ["the movement of the nation grows",
                 "a nation with great movement"] * 10
        w2v = (Word2Vec.builder()
               .iterate(CollectionSentenceIterator(sents))
               .tokenizer_factory(
                   PosFilterTokenizerFactory(["NN"], strip_nones=True))
               .layer_size(16).min_word_frequency(1).epochs(1)
               .seed(1).build())
        w2v.fit()
        assert w2v.has_word("movement") and w2v.has_word("nation")
        assert not w2v.has_word("the")
