"""Clustering / KNN / t-SNE / DeepWalk tests (VERDICT r2 item 6 done
criteria: VPTree/KMeans neighbour queries match brute force; t-SNE on
MNIST-1k yields a finite clustered embedding; DeepWalk similarity
sanity). Mirrors reference suites under nearestneighbor-core and
deeplearning4j-tsne tests.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    BarnesHutTsne,
    KDTree,
    KMeansClustering,
    RandomProjectionLSH,
    Tsne,
    VPTree,
    batched_knn,
    pairwise_distance,
)
from deeplearning4j_tpu.graph import (
    DeepWalk,
    Graph,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)


def blobs(n_per=50, centers=3, dim=8, seed=0, spread=0.3):
    rng = np.random.default_rng(seed)
    mus = rng.standard_normal((centers, dim)) * 4
    xs, ys = [], []
    for c in range(centers):
        xs.append(mus[c] + rng.standard_normal((n_per, dim)) * spread)
        ys.extend([c] * n_per)
    return np.concatenate(xs).astype(np.float32), np.asarray(ys)


def brute_knn(q, pts, k):
    d = np.linalg.norm(pts[None, :, :] - q[:, None, :], axis=-1)
    idx = np.argsort(d, axis=1)[:, :k]
    return np.take_along_axis(d, idx, 1), idx


# --------------------------------------------------------------------------
class TestDistances:
    def test_euclidean_matches_numpy(self):
        rng = np.random.default_rng(1)
        q = rng.standard_normal((7, 5)).astype(np.float32)
        p = rng.standard_normal((11, 5)).astype(np.float32)
        d = pairwise_distance(q, p)
        ref = np.linalg.norm(q[:, None] - p[None], axis=-1)
        np.testing.assert_allclose(d, ref, atol=1e-4)

    def test_knn_matches_brute_force(self):
        x, _ = blobs()
        rng = np.random.default_rng(2)
        q = rng.standard_normal((9, x.shape[1])).astype(np.float32)
        d, idx = batched_knn(q, x, 5)
        bd, bidx = brute_knn(q, x, 5)
        np.testing.assert_allclose(d, bd, atol=1e-3)
        np.testing.assert_array_equal(idx, bidx)

    def test_cosine_and_manhattan(self):
        rng = np.random.default_rng(3)
        q = rng.standard_normal((4, 6)).astype(np.float32)
        p = rng.standard_normal((8, 6)).astype(np.float32)
        dc = pairwise_distance(q, p, "cosinesimilarity")
        ref = 1 - (q @ p.T) / (
            np.linalg.norm(q, axis=1)[:, None] * np.linalg.norm(p, axis=1)[None]
        )
        np.testing.assert_allclose(dc, ref, atol=1e-4)
        dm = pairwise_distance(q, p, "manhattan")
        refm = np.abs(q[:, None] - p[None]).sum(-1)
        np.testing.assert_allclose(dm, refm, atol=1e-4)


class TestVPTree:
    def test_search_matches_brute_force(self):
        x, _ = blobs()
        tree = VPTree(x)
        rng = np.random.default_rng(4)
        q = rng.standard_normal(x.shape[1]).astype(np.float32)
        items, dists = tree.search(q, 7)
        bd, bidx = brute_knn(q[None], x, 7)
        np.testing.assert_allclose(dists, bd[0], atol=1e-3)
        np.testing.assert_allclose(items, x[bidx[0]], atol=1e-6)
        assert np.all(np.diff(dists) >= -1e-5)  # nearest first

    def test_kdtree(self):
        x, _ = blobs(n_per=20)
        t = KDTree(x.shape[1])
        for row in x:
            t.insert(row)
        assert t.size() == len(x)
        q = x[0] + 0.01
        nn, d = t.nn(q)
        np.testing.assert_allclose(nn, x[0], atol=1e-6)
        within = t.knn(q, 1.0)
        bd = np.linalg.norm(x - q, axis=1)
        assert len(within) == int((bd <= 1.0).sum())
        assert all(a[0] <= b[0] for a, b in zip(within, within[1:]))


class TestKMeans:
    def test_recovers_blobs(self):
        x, y = blobs(n_per=60, centers=4, seed=5)
        km = KMeansClustering.setup(4, max_iterations=50, seed=1)
        cs = km.apply_to(x)
        assert cs.centers.shape == (4, x.shape[1])
        assert np.isfinite(cs.inertia)
        # purity: each true cluster maps to one dominant k-means cluster
        purity = 0
        for c in range(4):
            assign_c = cs.assignments[y == c]
            purity += np.max(np.bincount(assign_c, minlength=4))
        assert purity / len(y) > 0.95

    def test_empty_cluster_reseeded(self):
        # k larger than natural clusters still returns k distinct centers
        x, _ = blobs(n_per=30, centers=2, seed=6)
        cs = KMeansClustering.setup(5, max_iterations=30, seed=2).apply_to(x)
        assert len(np.unique(cs.assignments)) >= 2
        assert np.all(np.isfinite(cs.centers))


class TestLSH:
    def test_bucket_recall_and_rerank(self):
        x, _ = blobs(n_per=100, centers=3, dim=16, seed=7)
        lsh = RandomProjectionLSH(hash_length=8, num_tables=6,
                                  dim=16, seed=3).make_index(x)
        q = x[10] + 0.01
        d, idx = lsh.search(q, 5)
        bd, bidx = brute_knn(q[None], x, 5)
        # approximate: the true NN must be found (q is right next to x[10])
        assert bidx[0, 0] in idx
        assert np.all(np.diff(d) >= -1e-5)


class TestTsne:
    @pytest.mark.slow
    def test_mnist_1k_clusters(self):
        """VERDICT criterion: t-SNE on MNIST-1k yields a finite clustered
        embedding (same-digit pairs closer than cross-digit pairs)."""
        from deeplearning4j_tpu.data.mnist import MnistDataSetIterator

        it = MnistDataSetIterator(1000, train=True, seed=1)
        ds = next(iter(it))
        x = np.asarray(ds.features).reshape(1000, -1)[:, ::4]  # light PCA-ish
        y = np.argmax(np.asarray(ds.labels), 1)
        emb = BarnesHutTsne.builder().set_max_iter(250).perplexity(30)\
            .theta(0.5).build().fit(x)
        assert emb.shape == (1000, 2)
        assert np.all(np.isfinite(emb))
        same, cross = [], []
        rng = np.random.default_rng(0)
        for _ in range(3000):
            i, j = rng.integers(0, 1000, 2)
            d = np.linalg.norm(emb[i] - emb[j])
            (same if y[i] == y[j] else cross).append(d)
        assert np.median(same) < 0.8 * np.median(cross)

    def test_synthetic_blobs_separate(self):
        x, y = blobs(n_per=40, centers=3, dim=10, seed=8, spread=0.2)
        ts = Tsne(max_iter=200, perplexity=15, seed=1)
        emb = ts.fit_transform(x)
        assert np.all(np.isfinite(emb))
        assert np.isfinite(ts.kl_divergence_)
        # cluster centroids separate further than intra-cluster spread
        cents = np.stack([emb[y == c].mean(0) for c in range(3)])
        intra = np.mean([emb[y == c].std(0).mean() for c in range(3)])
        inter = np.linalg.norm(
            cents[:, None] - cents[None], axis=-1
        )[np.triu_indices(3, 1)].mean()
        assert inter > 3 * intra


class TestGraphWalks:
    def _two_cliques(self):
        """Two 6-cliques joined by one bridge edge."""
        g = Graph(12)
        for base in (0, 6):
            for i in range(6):
                for j in range(i + 1, 6):
                    g.add_edge(base + i, base + j)
        g.add_edge(0, 6)
        return g

    def test_walk_properties(self):
        g = self._two_cliques()
        walks = list(RandomWalkIterator(g, walk_length=10, seed=1))
        assert len(walks) == 12
        for w in walks:
            assert len(w) == 10
            for a, b in zip(w, w[1:]):  # every step follows an edge
                assert b in g.get_connected_vertices(a) or a == b

    def test_weighted_walks_follow_weights(self):
        g = Graph(3)
        g.add_edge(0, 1, weight=100.0)
        g.add_edge(0, 2, weight=0.01)
        it = WeightedRandomWalkIterator(g, walk_length=2, seed=2,
                                        walks_per_vertex=50)
        nxt = [w[1] for w in it if w[0] == 0]
        assert np.mean(np.asarray(nxt) == 1) > 0.9

    def test_disconnected_self_loops(self):
        g = Graph(2)  # no edges
        walks = list(RandomWalkIterator(g, walk_length=4, seed=3))
        for w in walks:
            assert np.all(w == w[0])


class TestDeepWalk:
    def test_clique_structure_in_embeddings(self):
        g = TestGraphWalks()._two_cliques()
        dw = (
            DeepWalk.builder().vector_size(16).window_size(3)
            .walk_length(20).walks_per_vertex(20).learning_rate(0.05)
            .seed(4).epochs(3).build().fit(g)
        )
        within = np.mean([
            dw.similarity(i, j) for i in range(1, 6) for j in range(1, 6)
            if i != j
        ])
        across = np.mean([
            dw.similarity(i, j) for i in range(1, 6) for j in range(7, 12)
        ])
        assert within > across, f"within {within:.3f} <= across {across:.3f}"
        # nearest neighbours of a clique member are mostly its clique
        near = dw.vertices_nearest(2, 4)
        assert sum(v < 6 for v in near) >= 3

    def test_negative_sampling_variant(self):
        g = TestGraphWalks()._two_cliques()
        dw = (
            DeepWalk.builder().vector_size(8).window_size(2).walk_length(10)
            .walks_per_vertex(10).use_hierarchic_softmax(False)
            .negative_sample(5).seed(5).epochs(2).build().fit(g)
        )
        assert np.isfinite(dw.sv.last_loss)
        assert dw.get_vertex_vector(0).shape == (8,)

    def test_graph_vector_serializer_round_trip(self, tmp_path):
        """reference GraphVectorSerializer.writeGraphVectors /
        loadTxtVectors (tab-delimited text)."""
        from deeplearning4j_tpu.graph import GraphVectorSerializer

        g = TestGraphWalks()._two_cliques()
        dw = (
            DeepWalk.builder().vector_size(8).window_size(2).walk_length(10)
            .walks_per_vertex(5).seed(6).epochs(1).build().fit(g)
        )
        p = str(tmp_path / "gv.txt")
        GraphVectorSerializer.write_graph_vectors(dw, p)
        back = GraphVectorSerializer.load_txt_vectors(p)
        assert back.num_vertices() == dw.num_vertices()
        for v in range(dw.num_vertices()):
            np.testing.assert_allclose(
                back.get_vertex_vector(v), dw.get_vertex_vector(v),
                rtol=0, atol=1e-6)
        assert back.similarity(0, 1) == pytest.approx(
            dw.similarity(0, 1), abs=1e-5)
        # camelCase reference-parity aliases work too
        GraphVectorSerializer.writeGraphVectors(back, p + "2")
        again = GraphVectorSerializer.loadTxtVectors(p + "2")
        np.testing.assert_allclose(again.matrix, back.matrix, atol=1e-6)


class TestKnnServer:
    def test_http_knn_roundtrip(self):
        from deeplearning4j_tpu.clustering.server import (
            NearestNeighborsClient,
            NearestNeighborsServer,
        )

        x, _ = blobs(n_per=30, centers=2, dim=6, seed=11)
        srv = NearestNeighborsServer(x, port=0).start()
        try:
            client = NearestNeighborsClient(f"http://127.0.0.1:{srv.port}")
            res = client.knn(x[3] + 0.001, k=4)
            assert len(res) == 4
            assert res[0]["index"] == 3  # itself is nearest
            dists = [r["distance"] for r in res]
            assert dists == sorted(dists)
            bd, bidx = brute_knn((x[3] + 0.001)[None], x, 4)
            assert [r["index"] for r in res] == list(bidx[0])
        finally:
            srv.stop()

    def test_bad_request_is_400(self):
        import urllib.error
        import urllib.request

        from deeplearning4j_tpu.clustering.server import NearestNeighborsServer

        x, _ = blobs(n_per=10, centers=1, dim=4)
        srv = NearestNeighborsServer(x, port=0).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/knn", data=b"not json",
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()


class TestNode2Vec:
    def test_biased_walks_respect_pq(self):
        """p≫1 suppresses immediate backtracking; tiny p forces it."""
        from deeplearning4j_tpu.graph import BiasedRandomWalkIterator

        g = Graph(4)  # path graph 0-1-2-3
        for a, b in ((0, 1), (1, 2), (2, 3)):
            g.add_edge(a, b)
        returns = {}
        for p in (0.01, 100.0):
            it = BiasedRandomWalkIterator(g, walk_length=20, p=p, q=1.0,
                                          seed=3, walks_per_vertex=20)
            backtracks = total = 0
            for w in it:
                for i in range(2, len(w)):
                    if w[i] == w[i - 2] and w[i] != w[i - 1]:
                        backtracks += 1
                    total += 1
            returns[p] = backtracks / max(total, 1)
        assert returns[0.01] > returns[100.0] + 0.2, returns

    def test_node2vec_clique_structure(self):
        from deeplearning4j_tpu.graph import Node2Vec

        g = TestGraphWalks()._two_cliques()
        nv = (
            Node2Vec.builder().vector_size(16).window_size(3).walk_length(20)
            .walks_per_vertex(20).learning_rate(0.05).seed(4).epochs(3)
            .p(1.0).q(0.5).build().fit(g)
        )
        within = np.mean([
            nv.similarity(i, j) for i in range(1, 6) for j in range(1, 6)
            if i != j
        ])
        across = np.mean([
            nv.similarity(i, j) for i in range(1, 6) for j in range(7, 12)
        ])
        assert within > across


# --------------------------------------------------------------------------
class TestSpTree:
    """SpTree/QuadTree (reference clustering/sptree, clustering/quadtree):
    structural invariants + Barnes-Hut force evaluation vs the exact
    Student-t repulsion sum."""

    @staticmethod
    def exact_non_edge(data, i):
        dif = data[i] - np.delete(data, i, 0)
        q = 1.0 / (1.0 + np.sum(dif * dif, 1))
        return (q * q) @ dif, float(q.sum())

    def test_structure_and_com(self):
        from deeplearning4j_tpu.clustering import SpTree

        X, _ = blobs(n_per=40, centers=2, dim=3, seed=3)
        t = SpTree(X)
        assert t.get_cum_size() == len(X)
        np.testing.assert_allclose(t.get_center_of_mass(), X.mean(0),
                                   rtol=1e-5, atol=1e-5)
        assert t.is_correct()
        assert t.depth() >= 2

    def test_theta_zero_is_exact(self):
        from deeplearning4j_tpu.clustering import SpTree

        X, _ = blobs(n_per=25, centers=2, dim=2, seed=4)
        t = SpTree(X)
        for i in (0, 17, 49):
            f, z = t.compute_non_edge_forces(i, theta=0.0)
            f_ref, z_ref = self.exact_non_edge(X, i)
            np.testing.assert_allclose(f, f_ref, rtol=1e-4, atol=1e-5)
            assert abs(z - z_ref) < 1e-3

    def test_theta_half_approximates(self):
        from deeplearning4j_tpu.clustering import SpTree

        X, _ = blobs(n_per=60, centers=3, dim=2, seed=5, spread=0.5)
        t = SpTree(X)
        for i in (0, 90, 179):
            f, z = t.compute_non_edge_forces(i, theta=0.5)
            f_ref, z_ref = self.exact_non_edge(X, i)
            assert abs(z - z_ref) / z_ref < 0.1
            denom = np.linalg.norm(f_ref) + 1e-9
            assert np.linalg.norm(f - f_ref) / denom < 0.25

    def test_edge_forces_match_direct(self):
        from deeplearning4j_tpu.clustering import SpTree

        X, _ = blobs(n_per=20, centers=2, dim=2, seed=6)
        t = SpTree(X)
        rows = np.array([0, 0, 5, 39])
        cols = np.array([1, 2, 9, 0])
        vals = np.array([0.5, 0.25, 1.0, 0.125], np.float32)
        F = t.compute_edge_forces(rows, cols, vals)
        expected = np.zeros_like(X)
        for r, c, v in zip(rows, cols, vals):
            dif = X[r] - X[c]
            expected[r] += v * dif / (1.0 + dif @ dif)
        np.testing.assert_allclose(F, expected, rtol=1e-4, atol=1e-6)

    def test_quadtree_is_2d(self):
        from deeplearning4j_tpu.clustering import QuadTree

        X, _ = blobs(n_per=30, centers=2, dim=2, seed=7)
        q = QuadTree(X)
        assert q.is_correct() and q.get_cum_size() == 60
        center, half = q.get_boundary()
        assert center.shape == (2,) and np.all(half > 0)
        with pytest.raises(ValueError):
            QuadTree(np.zeros((4, 3), np.float32))


class TestRPForest:
    def test_leaf_exact_when_forest_covers_all(self):
        from deeplearning4j_tpu.clustering import RPTree

        X, _ = blobs(n_per=30, centers=2, dim=8, seed=8)
        t = RPTree(8, max_size=len(X))   # single leaf → exact
        t.build_tree(X)
        d, idx = t.query(X[7], k=5)
        d_ref, idx_ref = brute_knn(X[7:8], X, 5)
        np.testing.assert_array_equal(idx, idx_ref[0])
        np.testing.assert_allclose(d, d_ref[0], rtol=1e-4, atol=1e-5)

    def test_forest_recall(self):
        from deeplearning4j_tpu.clustering import RPForest

        X, _ = blobs(n_per=200, centers=4, dim=16, seed=9, spread=0.6)
        f = RPForest(num_trees=8, max_size=40).fit(X)
        qs = X[::37]
        d_ref, idx_ref = brute_knn(qs, X, 10)
        ds, idxs = f.query_all(qs, 10)
        recall = np.mean([len(set(a) & set(b)) / 10.0
                          for a, b in zip(idxs, idx_ref)])
        assert recall >= 0.9, f"RPForest recall {recall}"
        # distances are genuine euclidean distances of returned indices
        np.testing.assert_allclose(
            ds[0], np.linalg.norm(X[idxs[0]] - qs[0], axis=1), rtol=1e-4,
            atol=1e-5)

    def test_tree_depth_log(self):
        from deeplearning4j_tpu.clustering import RPTree

        rng = np.random.default_rng(10)
        X = rng.standard_normal((512, 4)).astype(np.float32)
        t = RPTree(4, max_size=16, seed=1)
        t.build_tree(X)
        assert 4 <= t.depth() <= 10  # balanced median splits → ~log2(512/16)+1


class TestTsneSparseLargeN:
    def test_sparse_path_separates_blobs(self):
        """BarnesHutTsne beyond dense_cutoff routes to the kNN-sparse +
        chunked-repulsion path and still separates well-separated blobs."""
        X, y = blobs(n_per=150, centers=3, dim=10, seed=11, spread=0.4)
        t = BarnesHutTsne(theta=0.5, dense_cutoff=100, chunk=128,
                          max_iter=250, perplexity=20.0, seed=2)
        Y = t.fit_transform(X)
        assert Y.shape == (450, 2)
        assert np.all(np.isfinite(Y))
        assert np.isfinite(t.kl_divergence_)
        # intra-cluster spread well under inter-cluster separation
        cents = np.stack([Y[y == c].mean(0) for c in range(3)])
        intra = max(np.linalg.norm(Y[y == c] - cents[c], axis=1).mean()
                    for c in range(3))
        inter = min(np.linalg.norm(cents[a] - cents[b])
                    for a in range(3) for b in range(a + 1, 3))
        assert inter > 2.0 * intra, (intra, inter)

    def test_sparse_matches_dense_quality(self):
        """On the same data, sparse-path KL should land near the dense
        exact path's KL (same approximation family as the reference's
        Barnes-Hut: sparse input affinities)."""
        X, _ = blobs(n_per=80, centers=3, dim=8, seed=12, spread=0.5)
        dense = BarnesHutTsne(theta=0.0, max_iter=200, perplexity=15.0, seed=3)
        dense.fit(X)
        sparse = BarnesHutTsne(theta=0.5, dense_cutoff=10, chunk=64,
                               max_iter=200, perplexity=15.0, seed=3)
        sparse.fit(X)
        assert sparse.kl_divergence_ < max(2.0 * dense.kl_divergence_, 0.5), (
            sparse.kl_divergence_, dense.kl_divergence_)

    def test_high_dim_builds(self):
        """d=30 must build without a dense 2^d child table (review
        finding: octant dicts, not a (4N, 2**d) array)."""
        from deeplearning4j_tpu.clustering import SpTree

        rng = np.random.default_rng(13)
        X = rng.standard_normal((200, 30)).astype(np.float32)
        t = SpTree(X, leaf_size=8)
        assert t.get_cum_size() == 200 and t.is_correct()
        f, z = t.compute_non_edge_forces(3, theta=0.0)
        f_ref, z_ref = TestSpTree.exact_non_edge(X, 3)
        np.testing.assert_allclose(f, f_ref, rtol=1e-4, atol=1e-5)
        assert abs(z - z_ref) < 1e-3


class TestBarnesHutBuilderTheta:
    def test_builder_theta_reaches_instance(self):
        t = (BarnesHutTsne.builder().theta(0.0).dense_cutoff(50).chunk(32)
             .set_max_iter(5).build())
        assert t.theta == 0.0 and t.dense_cutoff == 50 and t.chunk == 32
        t2 = BarnesHutTsne.builder().theta(0.7).build()
        assert t2.theta == 0.7


class TestSpTreeContainment:
    def test_theta_never_summarizes_containing_cell(self):
        """Review repro: two tight clusters at opposite corners in d=30 —
        the root cell contains the query point AND passes the bare theta
        criterion; summarizing it collapses the point's own neighbours
        into one far center-of-mass term (sum_Q 0.13 vs exact 48.8)."""
        from deeplearning4j_tpu.clustering import SpTree

        rng = np.random.default_rng(21)
        d = 30
        a = rng.standard_normal((50, d)).astype(np.float32) * 0.01
        b = 10.0 + rng.standard_normal((50, d)).astype(np.float32) * 0.01
        X = np.concatenate([a, b])
        t = SpTree(X, leaf_size=4)
        f, z = t.compute_non_edge_forces(0, theta=0.5)
        f_ref, z_ref = TestSpTree.exact_non_edge(X, 0)
        assert abs(z - z_ref) / z_ref < 0.1, (z, z_ref)
        denom = np.linalg.norm(f_ref) + 1e-9
        assert np.linalg.norm(f - f_ref) / denom < 0.3


class TestRPForestShortRows:
    def test_query_all_with_fewer_candidates_than_k(self):
        """Review repro: rows with < k candidates must clamp, not crash
        writing into a read-only JAX-backed numpy view."""
        from deeplearning4j_tpu.clustering import RPForest

        rng = np.random.default_rng(30)
        X = rng.standard_normal((30, 8)).astype(np.float32)
        f = RPForest(num_trees=1, max_size=1, search_k=3).fit(X)
        ds, idxs = f.query_all(X[:5], 8)
        assert ds.shape == (5, 8) and idxs.shape == (5, 8)
        assert np.all(np.isfinite(ds))
        assert np.all((idxs >= 0) & (idxs < 30))
        # clamped tail repeats the farthest real hit, monotone distances
        assert np.all(np.diff(ds, axis=1) >= -1e-5)


class TestGraphLoader:
    def test_edge_list_formats(self, tmp_path):
        """reference GraphLoader: edge-list / weighted / adjacency."""
        from deeplearning4j_tpu.graph import GraphLoader

        p = tmp_path / "edges.csv"
        p.write_text("# comment\n0,1\n1,2\n2,3\n")
        g = GraphLoader.load_undirected_graph_edge_list_file(str(p), 4)
        assert g.num_vertices() == 4
        assert sorted(g.get_connected_vertices(1)) == [0, 2]

        w = tmp_path / "weighted.csv"
        w.write_text("0,1,0.5\n1,2,2.0\n")
        gw = GraphLoader.load_weighted_edge_list_file(str(w), 3)
        assert gw.get_edge_weights(1) == [0.5, 2.0]
        gd = GraphLoader.load_weighted_edge_list_file(str(w), 3,
                                                     directed=True)
        assert gd.get_connected_vertices(1) == [2]  # 0->1 not reversed

        a = tmp_path / "adj.txt"
        a.write_text("0,1,2\n1,2\n2\n")
        ga = GraphLoader.load_adjacency_list_file(str(a), 3)
        assert sorted(ga.get_connected_vertices(0)) == [1, 2]
        assert ga.get_connected_vertices(2) == []

        # camelCase parity alias
        g2 = GraphLoader.loadUndirectedGraphEdgeListFile(str(p), 4)
        assert sorted(g2.get_connected_vertices(1)) == [0, 2]


class TestClusteringStrategy:
    def test_strategy_facade_runs_kmeans(self):
        """reference clustering-strategy framework: FixedClusterCount
        strategy + conditions drive the same MXU k-means."""
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm,
            ConvergenceCondition,
            FixedClusterCountStrategy,
        )

        x, y = blobs(n_per=50, centers=3, seed=12)
        strat = (FixedClusterCountStrategy.setup(3, "euclidean")
                 .end_when_iteration_count_equals(40).with_seed(7))
        cs = BaseClusteringAlgorithm.setup(strat).apply_to(x)
        assert cs.centers.shape == (3, x.shape[1])
        purity = sum(
            np.max(np.bincount(cs.assignments[y == c], minlength=3))
            for c in range(3)) / len(y)
        assert purity > 0.95

        strat2 = (FixedClusterCountStrategy.setup(3)
                  .end_when_distribution_variation_rate_less_than(1e-3))
        assert isinstance(strat2.termination, ConvergenceCondition)
        cs2 = BaseClusteringAlgorithm.setup(strat2).applyTo(x)
        assert np.all(np.isfinite(cs2.centers))
