"""ZeRO-1 cross-replica sharded weight update (parallel/zero.py).

Parity model: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336) is a pure optimization — the
sharded update must be NUMERICALLY the replicated update (elementwise
updater math on 1/N flat shards, reduce-scatter + all-gather moving the
same bytes as the all-reduce it replaces). Every test here trains the
same net twice, sharded vs replicated, on the virtual 8-device CPU mesh
(conftest.py) and asserts allclose — including through a checkpoint
save→load→resume and with bf16 compute + fp32 masters.

Also carries the satellite regressions riding the same PR: binary
micro-F1, estimator partial_fit label normalization, and hasBias=false
dense slicing in the dl4j zip loader.
"""

import numpy as np
import pytest

import java_interop_fixture as fx
from deeplearning4j_tpu.data import DataSet, ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelWrapper
from deeplearning4j_tpu.updaters import Adam

N_IN, N_HID, N_OUT = 5, 7, 3


def _net(seed=3, mixed_precision=False, updater=None):
    b = NeuralNetConfiguration.builder().seed(seed).updater(
        updater if updater is not None else Adam(0.01))
    if mixed_precision:
        b = b.compute_dtype("bfloat16")
    conf = (
        b.list()
        .layer(DenseLayer(n_out=N_HID, activation="tanh"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _blobs(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, n)]
    return DataSet(x, y)


def _assert_trees_close(a, b, atol=1e-6):
    for i, (pa, pb) in enumerate(zip(a, b)):
        for k in pa:
            np.testing.assert_allclose(
                np.asarray(pa[k]), np.asarray(pb[k]), atol=atol,
                err_msg=f"layer {i} param {k}")


def _fit_pair(mixed_precision=False, workers=4, epochs=3):
    """The same net trained replicated vs ZeRO-1 sharded; returns both."""
    ds = _blobs()
    ref, zer = (_net(mixed_precision=mixed_precision) for _ in range(2))
    ParallelWrapper.builder(ref).workers(workers).build().fit(
        ExistingDataSetIterator([ds]), epochs=epochs)
    pw = ParallelWrapper.builder(zer).workers(workers).sharded_update(
        True).build()
    pw.fit(ExistingDataSetIterator([ds]), epochs=epochs)
    return ref, zer, pw


class TestWrapperParity:
    def test_adam_fp32_parity(self):
        ref, zer, _ = _fit_pair()
        _assert_trees_close(ref.params_, zer.params_)
        # gathered-back opt state is the canonical per-layer format and
        # matches the replicated run's slots (checkpoint contract)
        for i in range(len(ref.opt_state_)):
            for k, slots in ref.opt_state_[i].items():
                for s in slots:
                    np.testing.assert_allclose(
                        np.asarray(slots[s]),
                        np.asarray(zer.opt_state_[i][k][s]), atol=1e-6,
                        err_msg=f"opt layer {i} {k}/{s}")

    def test_mixed_precision_parity(self):
        """bf16 compute, fp32 masters + fp32 updater math — the sharded
        update runs on the fp32 masters, so parity stays exact."""
        ref, zer, _ = _fit_pair(mixed_precision=True)
        _assert_trees_close(ref.params_, zer.params_)
        assert all(np.asarray(v).dtype == np.float32
                   for p in zer.params_ for v in p.values())

    def test_odd_param_count_pads(self):
        """Total trainable count (5*7+7 + 7*3+3 = 66) is not divisible
        by 4 shards — the flat vector zero-pads and parity still holds."""
        ref, zer, pw = _fit_pair(workers=4)
        assert pw._zlayout is not None
        assert pw._zlayout.n_padding() > 0
        _assert_trees_close(ref.params_, zer.params_)

    def test_config_knob_enables_sharding(self):
        """NeuralNetConfiguration.sharded_update(True) flows through the
        builder default."""
        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .sharded_update(True)
            .list()
            .layer(DenseLayer(n_out=N_HID, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build()
        )
        m = MultiLayerNetwork(conf).init()
        pw = ParallelWrapper.builder(m).workers(4).build()
        assert pw.sharded_update
        pw.fit(ExistingDataSetIterator([_blobs()]), epochs=1)
        assert pw._zlayout is not None
        # knob round-trips through conf JSON (checkpoint restore path)
        clone = type(m.conf).from_json(m.conf.to_json())
        assert clone.global_conf.sharded_update is True

    def test_midfit_checkpoint_listener_gathers_opt_state(self, tmp_path):
        """A CheckpointListener firing DURING a sharded fit must save the
        canonical gathered opt state of that iteration, not the stale
        pre-fit copy (serializers call the _opt_state_sync hook)."""
        from deeplearning4j_tpu.train.listeners import CheckpointListener
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        ds = _blobs()
        ref = _net()
        ParallelWrapper.builder(ref).workers(4).build().fit(
            ExistingDataSetIterator([ds]), epochs=2)

        zer = _net()
        lst = CheckpointListener(str(tmp_path), save_every_n_iterations=2)
        zer.listeners.append(lst)
        ParallelWrapper.builder(zer).workers(4).sharded_update(
            True).build().fit(ExistingDataSetIterator([ds]), epochs=4)
        assert zer._opt_state_sync is None  # hook cleared after fit

        mid = ModelSerializer.restore_multi_layer_network(lst.checkpoints[0])
        assert mid.iteration == 2
        np.testing.assert_allclose(mid.opt_state_flat(),
                                   ref.opt_state_flat(), atol=1e-6)
        np.testing.assert_allclose(mid.params_flat(), ref.params_flat(),
                                   atol=1e-6)

    def test_save_load_resume_roundtrip(self, tmp_path):
        """2 sharded epochs → ModelSerializer save → restore → 2 more
        sharded epochs == 4 uninterrupted replicated epochs."""
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        ds = _blobs()
        ref = _net()
        ParallelWrapper.builder(ref).workers(4).build().fit(
            ExistingDataSetIterator([ds]), epochs=4)

        zer = _net()
        pw = ParallelWrapper.builder(zer).workers(4).sharded_update(
            True).build()
        pw.fit(ExistingDataSetIterator([ds]), epochs=2)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(zer, path, save_updater=True)

        resumed = ModelSerializer.restore_multi_layer_network(path)
        assert resumed.iteration == 2 and resumed.epoch == 2
        pw2 = ParallelWrapper.builder(resumed).workers(4).sharded_update(
            True).build()
        pw2.fit(ExistingDataSetIterator([ds]), epochs=2)
        _assert_trees_close(ref.params_, resumed.params_)


class TestSharedMasterSharded:
    def test_threshold_encoding_parity(self):
        """Sharded vs replicated update consuming the same
        threshold-decoded gradient — wire format unchanged, params equal."""
        from deeplearning4j_tpu.parallel import SharedTrainingMaster

        ds = _blobs()
        nets = []
        for sharded in (False, True):
            m = _net()
            master = (SharedTrainingMaster.builder(1e-5)
                      .sharded_update(sharded).build())
            master.fit(m, ExistingDataSetIterator([ds]), epochs=3)
            nets.append(m)
        _assert_trees_close(nets[0].params_, nets[1].params_)

    def test_conf_knob_enables_sharding(self):
        """The NeuralNetConfiguration.sharded_update knob reaches a
        default-built SharedTrainingMaster too."""
        from deeplearning4j_tpu.parallel import SharedTrainingMaster

        conf = (
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .sharded_update(True)
            .list()
            .layer(DenseLayer(n_out=N_HID, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build()
        )
        m = MultiLayerNetwork(conf).init()
        master = SharedTrainingMaster.builder(1e-5).build()
        master.fit(m, ExistingDataSetIterator([_blobs()]), epochs=1)
        assert master._layout is not None


class TestMultiHostMasterSharded:
    def test_parameter_averaging_master_parity(self):
        from deeplearning4j_tpu.parallel import (
            MultiHostNetwork,
            ParameterAveragingTrainingMaster,
        )

        ds = _blobs()
        nets = []
        for sharded in (False, True):
            m = _net()
            master = (ParameterAveragingTrainingMaster.Builder()
                      .batch_size_per_worker(4)
                      .sharded_update(sharded).build())
            MultiHostNetwork(m, master).fit(
                ExistingDataSetIterator([ds]), epochs=3)
            nets.append(m)
        _assert_trees_close(nets[0].params_, nets[1].params_)


class TestTransformerDataAxis:
    V, T, B = 31, 16, 8

    def _data(self, seed=0):
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, self.V, (self.B, self.T)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tgt[:, -1] = -1
        return ids, tgt

    def _model(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        return TransformerLM(vocab_size=self.V, d_model=32, n_heads=4,
                             n_layers=2, max_length=self.T).init()

    def test_data_axis_parity_and_sharded_opt_state(self):
        import jax

        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import (
            DistributedLMTrainer,
        )

        ids, tgt = self._data()
        runs = {}
        for sharded in (False, True):
            tr = DistributedLMTrainer(self._model(), TrainingMesh(data=8),
                                      sharded_update=sharded).place()
            losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
            runs[sharded] = (tr, losses)
        np.testing.assert_allclose(runs[False][1], runs[True][1],
                                   rtol=1e-5, atol=1e-6)
        p_ref = jax.tree_util.tree_leaves(runs[False][0].model.params_)
        p_z = jax.tree_util.tree_leaves(runs[True][0].model.params_)
        for a, b in zip(p_ref, p_z):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        # the ZeRO-1 run must actually hold opt state sharded over "data"
        leaves = jax.tree_util.tree_leaves(runs[True][0].model.opt_state_)
        specs = [getattr(l.sharding, "spec", None) for l in leaves]
        n_data_sharded = sum(
            1 for s in specs if s is not None and any(
                e == "data" or (isinstance(e, (list, tuple)) and "data" in e)
                for e in s if e is not None))
        assert n_data_sharded > 0
        dev0 = jax.devices()[0]
        z_bytes = sum(s.data.nbytes
                      for l in jax.tree_util.tree_leaves(
                          runs[True][0].model.opt_state_)
                      for s in l.addressable_shards if s.device == dev0)
        r_bytes = sum(s.data.nbytes
                      for l in jax.tree_util.tree_leaves(
                          runs[False][0].model.opt_state_)
                      for s in l.addressable_shards if s.device == dev0)
        assert z_bytes < r_bytes  # measurably less opt state per replica

    def test_zero1_extend_spec(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.zero import zero1_extend_spec

        def entries(spec):
            return tuple(spec)

        # first free dim divisible by n gets "data"
        assert entries(zero1_extend_spec(P(), (16, 3), 8)) == ("data", None)
        assert zero1_extend_spec(P(None, "model"), (7, 32), 4) is None
        assert entries(zero1_extend_spec(P("model"), (32, 16), 8)) == (
            "model", "data")
        # axis already used, or no divisible dim → leaf stays as-is
        assert zero1_extend_spec(P("data"), (16, 16), 8) is None
        assert zero1_extend_spec(P(), (3, 5), 8) is None
        assert zero1_extend_spec(P(), (16,), 1) is None


class TestMemoryEstimator:
    def test_updater_state_scales_inverse_n(self):
        from deeplearning4j_tpu.nn.conf.memory import memory_report_mln

        rep = memory_report_mln(_net().conf)
        full = rep.updater_state_bytes()
        shard = rep.updater_state_bytes(data_parallel_shards=8)
        assert full > 0
        # 1/N with per-layer ceil: never less than total/N, close to it
        assert full / 8 <= shard <= full / 8 + 8 * 4 * len(rep.layer_reports)
        assert (rep.total_memory_bytes(32, True)
                - rep.total_memory_bytes(32, True, data_parallel_shards=8)
                == full - shard)
        # inference memory has no updater slots to shard
        assert rep.total_memory_bytes(32, False) == rep.total_memory_bytes(
            32, False, data_parallel_shards=8)

    def test_to_string_reports_saving(self):
        from deeplearning4j_tpu.nn.conf.memory import memory_report_mln

        s = memory_report_mln(_net().conf).to_string(
            batch_size=32, data_parallel_shards=8)
        assert "sharded_update over 8 replicas" in s


class TestSatelliteRegressions:
    def test_binary_micro_f1_uses_positive_class(self):
        """reference Evaluation.fBeta: 2-class problems return class-1 F1
        regardless of the averaging mode, micro included."""
        from deeplearning4j_tpu.evaluation import Evaluation

        labels = np.eye(2, dtype=np.float32)[[0, 0, 0, 1, 1, 0, 1, 1]]
        preds = np.eye(2, dtype=np.float32)[[0, 1, 0, 1, 0, 0, 1, 1]]
        ev = Evaluation()
        ev.eval(labels, preds)
        assert ev.f1(averaging="micro") == pytest.approx(ev.f1(1))
        assert ev.f1(averaging="macro") == pytest.approx(ev.f1(1))
        p1, r1 = ev.precision(1), ev.recall(1)
        assert ev.f1(1) == pytest.approx(2 * p1 * r1 / (p1 + r1))

    def test_estimator_partial_fit_unsorted_classes(self):
        from deeplearning4j_tpu.estimator import NeuralNetClassifier

        def conf():
            return (
                NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build()
            )

        rng = np.random.default_rng(0)
        centers = rng.standard_normal((3, 4)) * 3
        y = rng.integers(0, 3, 48)
        x = (centers[y] + rng.standard_normal((48, 4)) * 0.1).astype(
            np.float32)

        est = NeuralNetClassifier(conf, epochs=1)
        # unsorted classes= must not scramble the label→column mapping
        for _ in range(30):
            est.partial_fit(x, y, classes=[2, 0, 1])
        assert list(est.classes_) == [0, 1, 2]
        assert np.mean(est.predict(x) == y) > 0.9

        with pytest.raises(ValueError, match="not in classes="):
            est.partial_fit(x, np.full_like(y, 7))

    def test_loader_dense_without_bias(self, tmp_path):
        """hasBias=false dense zips carry no bias values; consuming them
        anyway would mis-slice every subsequent parameter."""
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_java_multi_layer_network,
        )

        p = fx.mlp_params()
        path = str(tmp_path / "nb.zip")
        with open(path, "wb") as f:
            f.write(fx.mlp_nobias_zip_bytes())
        net = restore_java_multi_layer_network(path)
        x = np.random.default_rng(5).normal(size=(9, 4)).astype(np.float32)
        got = np.asarray(net.output(x))
        want = fx.mlp_nobias_forward_numpy(p, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params_[0]["b"]), 0.0)
