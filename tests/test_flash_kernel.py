"""Hand-written Pallas flash-attention kernel (nn/ops/flash_attention.py)
— parity vs dense XLA attention through the Pallas interpreter on the CPU
mesh (the kernel itself targets TPU; real-hardware parity is driven by
the round's verify runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers.attention import dense_attention
from deeplearning4j_tpu.nn.ops.flash_attention import (
    MAX_SEQ_LEN,
    flash_attention,
)


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFlashKernelInterpret:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        b, h, T, hd = 2, 3, 256, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=causal, interpret=True)
        o_d = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        b, h, T, hd = 1, 2, 128, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        do = _rand((b, h, T, hd), 7)

        def loss_f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) * do)

        def loss_d(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=causal) * do)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_head_dim_padding(self):
        """hd=48 (not a lane multiple) is zero-padded internally and the
        result is identical to dense."""
        q, k, v = (_rand((1, 2, 128, 48), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=True, interpret=True)
        assert o_f.shape == (1, 2, 128, 48)
        o_d = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    def test_sm_scale_override(self):
        q, k, v = (_rand((1, 1, 128, 64), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=False, sm_scale=0.25,
                              interpret=True)
        T = 128
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.25
        o_d = jnp.einsum("bhqk,bhkd->bhqd",
                         jax.nn.softmax(scores, -1), v)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    def test_shape_validation(self):
        q = jnp.zeros((1, 1, 100, 64))
        with pytest.raises(ValueError, match="multiple of 128"):
            flash_attention(q, q, q, interpret=True)
        big = jnp.zeros((1, 1, MAX_SEQ_LEN + 128, 64))
        with pytest.raises(ValueError, match="ring attention"):
            flash_attention(big, big, big, interpret=True)
        k = jnp.zeros((1, 1, 256, 64))
        with pytest.raises(ValueError, match="match exactly"):
            flash_attention(jnp.zeros((1, 1, 128, 64)), k, k,
                            interpret=True)

    def test_block_mixing_multiblock(self):
        """T=384 exercises the 128-block path with 3 kv blocks and a
        non-trivial causal loop bound."""
        q, k, v = (_rand((1, 2, 384, 64), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=True, interpret=True)
        o_d = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)
