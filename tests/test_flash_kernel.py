"""Hand-written Pallas flash-attention kernel (nn/ops/flash_attention.py)
— parity vs dense XLA attention through the Pallas interpreter on the CPU
mesh (the kernel itself targets TPU; real-hardware parity is driven by
the round's verify runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers.attention import dense_attention
from deeplearning4j_tpu.nn.ops.flash_attention import (
    MAX_SEQ_LEN,
    flash_attention,
)


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFlashKernelInterpret:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        b, h, T, hd = 2, 3, 256, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=causal, interpret=True)
        o_d = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        b, h, T, hd = 1, 2, 128, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        do = _rand((b, h, T, hd), 7)

        def loss_f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) * do)

        def loss_d(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=causal) * do)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_head_dim_padding(self):
        """hd=48 (not a lane multiple) is zero-padded internally and the
        result is identical to dense."""
        q, k, v = (_rand((1, 2, 128, 48), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=True, interpret=True)
        assert o_f.shape == (1, 2, 128, 48)
        o_d = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    def test_sm_scale_override(self):
        q, k, v = (_rand((1, 1, 128, 64), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=False, sm_scale=0.25,
                              interpret=True)
        T = 128
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.25
        o_d = jnp.einsum("bhqk,bhkd->bhqd",
                         jax.nn.softmax(scores, -1), v)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    def test_shape_validation(self):
        q = jnp.zeros((1, 1, 100, 64))
        with pytest.raises(ValueError, match="multiple of 128"):
            flash_attention(q, q, q, interpret=True)
        big = jnp.zeros((1, 1, MAX_SEQ_LEN + 128, 64))
        with pytest.raises(ValueError, match="ring attention"):
            flash_attention(big, big, big, interpret=True)
        k = jnp.zeros((1, 1, 256, 64))
        with pytest.raises(ValueError, match="match exactly"):
            flash_attention(jnp.zeros((1, 1, 128, 64)), k, k,
                            interpret=True)

    def test_block_mixing_multiblock(self):
        """T=384 exercises the 128-block path with 3 kv blocks and a
        non-trivial causal loop bound."""
        q, k, v = (_rand((1, 2, 384, 64), i) for i in range(3))
        o_f = flash_attention(q, k, v, causal=True, interpret=True)
        o_d = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)


def _dense_seg_ref(q, k, v, seg, causal):
    """Independent einsum reference with the same-segment mask."""
    T = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e30)
    same = seg[:, None, :, None] == seg[:, None, None, :]
    s = jnp.where(same, s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


class TestFlashSegmentIds:
    """r5: packed-sequence (segment-id) support in the Pallas kernel —
    interpret-mode parity vs an independent masked-einsum reference."""

    def _seg(self, b, T, cuts):
        seg = np.zeros((b, T), np.int32)
        for i, c in enumerate(cuts):
            seg[:, c:] = i + 1
        return jnp.asarray(seg)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        b, h, T, hd = 2, 2, 384, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        # cuts at 150 and 290: both interior to 128-blocks (block mixing)
        seg = self._seg(b, T, [150, 290])
        o_f = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                              interpret=True)
        o_d = _dense_seg_ref(q, k, v, seg, causal)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_reference(self, causal):
        b, h, T, hd = 1, 2, 256, 64
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        do = _rand((b, h, T, hd), 7)
        seg = self._seg(b, T, [100])

        def loss_f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, segment_ids=seg,
                interpret=True) * do)

        def loss_d(q, k, v):
            return jnp.sum(_dense_seg_ref(q, k, v, seg, causal) * do)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_per_row_segments_differ(self):
        """each batch row carries its own packing boundaries"""
        b, h, T, hd = 2, 1, 128, 32
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        seg = np.zeros((b, T), np.int32)
        seg[0, 40:] = 1
        seg[1, 90:] = 1
        seg = jnp.asarray(seg)
        o_f = flash_attention(q, k, v, causal=True, segment_ids=seg,
                              interpret=True)
        o_d = _dense_seg_ref(q, k, v, seg, True)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)

    def test_segment_shape_validation(self):
        q = jnp.zeros((1, 1, 128, 64))
        with pytest.raises(ValueError, match="segment_ids"):
            flash_attention(q, q, q, segment_ids=jnp.zeros((1, 64)),
                            interpret=True)

    def test_dense_attention_segment_fallback(self):
        """dense_attention's einsum and blocked paths honor segment_ids
        (the CPU fallback for the kernel's packed-sequence mode)."""
        b, h, T, hd = 1, 2, 1024, 32
        q, k, v = (_rand((b, h, T, hd), i) for i in range(3))
        seg = self._seg(b, T, [700])
        # T=1024 >= BLOCKED_ATTENTION_MIN_T -> blocked path on CPU
        o_b = dense_attention(q, k, v, causal=True, segment_ids=seg)
        o_d = _dense_seg_ref(q, k, v, seg, True)
        np.testing.assert_allclose(np.asarray(o_b), np.asarray(o_d),
                                   rtol=1e-5, atol=2e-5)


class TestPackedSequenceLM:
    """Packed-sequence LM training (VERDICT r4 #6): segment isolation is
    checked against a no-packing oracle — logits of document A at the
    head of a packed row equal A trained alone (causality + the segment
    mask make the rest of the row invisible)."""

    def test_segment_isolation_oracle(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            TransformerLM, forward)
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            dense_attention as da)

        m = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                          n_layers=2, max_length=128, seed=3).init()
        rng = np.random.default_rng(0)
        T, t1 = 128, 50
        packed = rng.integers(0, 64, (1, T)).astype(np.int32)
        seg = np.zeros((1, T), np.int32)
        seg[0, t1:] = 1

        def attn_seg(q, k, v, *, causal, mask=None):
            return da(q, k, v, causal=causal, mask=mask,
                      segment_ids=jnp.asarray(seg))

        lp = np.asarray(forward(m.cfg, m.params_, jnp.asarray(packed),
                                attn_fn=attn_seg))
        # doc A alone in the same positions (suffix tokens are invisible
        # to positions < t1 under the causal mask)
        la = np.asarray(forward(m.cfg, m.params_, jnp.asarray(packed)))
        np.testing.assert_allclose(lp[0, :t1], la[0, :t1],
                                   rtol=1e-4, atol=1e-5)
        # ...while doc B's logits DO differ (its attention was cut)
        assert np.abs(lp[0, t1:] - la[0, t1:]).max() > 1e-3

    def test_fit_batch_with_segments_trains(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM

        m = TransformerLM(vocab_size=32, d_model=32, n_heads=4,
                          n_layers=2, max_length=64, seed=1).init()
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 32, (4, 64)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        seg = np.zeros((4, 64), np.int32)
        seg[:, 32:] = 1
        tgt[:, 31] = -1  # boundary token must not predict across docs
        tgt[:, -1] = -1
        losses = [m.fit_batch(ids, tgt, segment_ids=seg)
                  for _ in range(8)]
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]
