"""Worker for the distributed-ParagraphVectors parity test (capability
match for the reference's Spark ParagraphVectors,
``dl4j-spark-nlp/.../paragraphvectors/``): each process builds the SAME
labelled corpus, trains doc2vec on its document shard, and synchronizes
at epoch boundaries — word rows parameter-averaged, label rows combined
by document ownership. ``pv.fit()`` is called directly: the auto-route
through DistributedParagraphVectors when ``jax.process_count() > 1`` is
part of what this worker proves.

Usage: python multihost_pv_worker.py <coordinator> <nprocs> <pid> <outdir>
"""

import os
import sys

coordinator, nprocs, pid, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel.multihost import initialize  # noqa: E402
from tests.pv_corpus import build_docs, build_pv  # noqa: E402

ctx = initialize(coordinator, num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs

docs = build_docs()
pv = build_pv(docs).fit()  # auto-routes: process_count > 1

V = pv._n_words
labels = [f"DOC_{i}" for i in range(len(docs))]
label_vecs = np.stack([pv.get_paragraph_vector(l) for l in labels])
syn0 = np.asarray(pv.sv.syn0)

suffix = "" if pid == 0 else f"_{pid}"
np.savez(os.path.join(outdir, f"pv_dist{suffix}.npz"),
         syn0=syn0, label_vecs=label_vecs, n_words=V)
print(f"pv worker {pid}: done, V={V}", flush=True)
