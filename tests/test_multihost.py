"""Multi-host training parity test — port of the reference's
``TestCompareParameterAveragingSparkVsSingleMachine.java`` (SURVEY.md
§4.5): the SAME net trained (a) across 2 separate processes × 2 CPU
devices on a global mesh via jax.distributed, and (b) in a single process,
must end with matching parameters.

The 2-process run exercises the real multi-host stack: coordinator
bootstrap, Gloo cross-process collectives, host-local→global array
assembly, checkpoint save/restore barrier.
"""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

HERE = os.path.dirname(__file__)


def _run_two_workers(script_name: str, prefix: str, extra_args=()):
    """Launch two worker processes against a fresh coordinator; returns
    (outdir, outputs) after asserting both exit 0."""
    from deeplearning4j_tpu.parallel.multihost import free_port

    port = free_port()
    outdir = tempfile.mkdtemp(prefix=prefix)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script_name),
             f"127.0.0.1:{port}", "2", str(pid), outdir, *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    return outdir, outs


@pytest.mark.slow
def test_two_process_training_matches_single_process():
    outdir, _ = _run_two_workers("multihost_worker.py", "mh_parity_")

    result = np.load(os.path.join(outdir, "multihost_result.npz"))
    assert result["iteration"] == 12  # 3 epochs × 4 global batches
    assert result["n_stats"] > 0  # collect_training_stats plumbing
    assert np.isfinite(result["score"])
    # distributed evaluation merged over BOTH hosts' shards: the count
    # covers the full dataset and both hosts agree exactly
    r1 = np.load(os.path.join(outdir, "multihost_result_1.npz"))
    assert int(result["eval_total"]) == 64  # GLOBAL_BATCH * N_BATCHES
    assert float(result["eval_accuracy"]) == float(r1["eval_accuracy"])
    assert int(r1["eval_total"]) == 64

    # single-process reference: same net, same global batches, 3 epochs
    from tests.multihost_model import build_net, global_batches

    net = build_net()
    it = global_batches()
    for _ in range(3):
        net._fit_one_epoch(it)
    single = net.params_flat()

    multi = result["params"]
    assert multi.shape == single.shape
    # fp32 CPU vs fp32 Gloo-reduced: tolerances cover reduction-order noise
    np.testing.assert_allclose(multi, single, atol=1e-4, rtol=1e-3)
    # and training moved the params (not trivially passing on init state)
    init = build_net().params_flat()
    assert np.abs(single - init).max() > 1e-3


@pytest.mark.slow
def test_two_process_compressed_gradient_training():
    """SharedTrainingMaster across 2 processes: threshold-encoded updates
    cross hosts via the gathered messages; both processes converge and
    END WITH IDENTICAL PARAMETERS (the decode is deterministic and
    symmetric — the reference's SharedTraining consistency property)."""
    outdir, _ = _run_two_workers("multihost_shared_worker.py", "mh_shared_")

    r0 = np.load(os.path.join(outdir, "shared_result_0.npz"))
    r1 = np.load(os.path.join(outdir, "shared_result_1.npz"))
    assert r0["last"] < 0.6 * r0["first"], (r0["first"], r0["last"])
    # bit-identical replicas across hosts
    np.testing.assert_allclose(r0["params"], r1["params"], atol=0)


@pytest.mark.slow
def test_two_process_orbax_cooperative_checkpoint():
    """Cooperative Orbax save from a 2-process global mesh + restore onto
    a placed template (OrbaxModelSerializer's multi-host contract)."""
    outdir, _ = _run_two_workers("multihost_orbax_worker.py", "mh_orbax_")
    for pid in range(2):
        assert os.path.exists(os.path.join(outdir, f"orbax_ok_{pid}"))


@pytest.mark.slow
def test_kill_one_process_then_resume_from_checkpoint():
    """Fault injection + recovery (VERDICT r3 item 8 + ISSUE 2): SIGKILL
    one of two training processes mid-epoch, observe the survivor cannot
    finish (collective peer loss), TRUNCATE the newest checkpoint (the
    on-disk state a crash mid-write would leave without atomic replace),
    then restart a fresh pair — the workers must recover through
    train.faults.latest_valid_checkpoint (skipping the corrupt newest zip
    back to the previous good one) and end with parameters equal to an
    uninterrupted run's bit-for-bit. The reference has no fault-injection
    test at all (SURVEY §4.5)."""
    import signal
    import time as _time

    from deeplearning4j_tpu.parallel.multihost import free_port

    outdir = tempfile.mkdtemp(prefix="mh_fault_")
    script = os.path.join(HERE, "multihost_faulttol_worker.py")

    def launch(phase):
        port = free_port()
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        return [
            subprocess.Popen(
                [sys.executable, script, f"127.0.0.1:{port}", "2", str(pid),
                 outdir, phase],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
            for pid in range(2)
        ]

    # uninterrupted reference run
    for pid, p in enumerate(launch("full")):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"full worker {pid}:\n{out.decode()[-3000:]}"

    # crash run: wait until both workers are inside epoch 2, then kill #1
    procs = launch("crash")
    deadline = _time.time() + 300
    while _time.time() < deadline and not all(
            os.path.exists(os.path.join(outdir, f"epoch2_{i}"))
            for i in range(2)):
        _time.sleep(0.1)
        assert all(p.poll() is None for p in procs), "crash worker died early"
    _time.sleep(0.7)  # land inside a batch/collective
    procs[1].send_signal(signal.SIGKILL)
    procs[1].wait()
    try:  # the survivor must fail or hang — never complete the epoch
        procs[0].communicate(timeout=90)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].communicate()
    assert not os.path.exists(os.path.join(outdir, "final_crash_0.npz")), \
        "worker 0 finished training despite its peer being killed"

    # corrupt the NEWEST checkpoint: the resume workers must detect the
    # truncation and fall back to the previous good one (ISSUE 2)
    from deeplearning4j_tpu.train import faults

    newest = os.path.join(outdir, "ckpts", "ft_ckpt_b.zip")
    assert faults.is_valid_checkpoint(newest)
    faults.truncate_file(newest)
    assert not faults.is_valid_checkpoint(newest)

    # recovery: fresh pair restores the latest VALID checkpoint and
    # completes epoch 2
    for pid, p in enumerate(launch("resume")):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"resume worker {pid}:\n{out.decode()[-3000:]}"

    full = np.load(os.path.join(outdir, "final_full_0.npz"))
    resumed = np.load(os.path.join(outdir, "final_resume_0.npz"))
    assert int(resumed["iteration"]) == int(full["iteration"])
    np.testing.assert_allclose(resumed["params"], full["params"], atol=0)


@pytest.mark.slow
def test_sixteen_virtual_devices_full_mesh():
    """TP x PP x SP x DP on 16 virtual devices + MoE EP composed with
    dp/tp (VERDICT r3 item 8): own process so the device count can exceed
    the suite's 8; the worker asserts single-device parity internally."""
    outdir = tempfile.mkdtemp(prefix="mc16_")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    p = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multichip16_worker.py"), outdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    out, _ = p.communicate(timeout=600)
    assert p.returncode == 0, out.decode()[-3000:]
    assert os.path.exists(os.path.join(outdir, "ok"))


@pytest.mark.slow
def test_two_process_sequence_vectors_similarity_parity():
    """Distributed embedding training (VERDICT r3 item 6, the
    dl4j-spark-nlp Word2VecPerformer capability): 2 processes train
    skip-gram on disjoint sentence shards with epoch-boundary parameter
    averaging; the result must (a) end bit-identical across replicas,
    (b) recover the same similarity structure as single-process training
    on the full corpus."""
    from tests.seqvec_corpus import build_corpus_and_vocab, topic_separation

    outdir, _ = _run_two_workers("multihost_seqvec_worker.py", "mh_seqvec_")
    d0 = np.load(os.path.join(outdir, "seqvec_dist.npz"))
    d1 = np.load(os.path.join(outdir, "seqvec_dist_1.npz"))
    np.testing.assert_allclose(d0["syn0"], d1["syn0"], atol=0)  # replicas agree
    # the Word2Vec FACADE also ran distributed (auto-routed): replicas agree
    np.testing.assert_allclose(d0["w2v"], d1["w2v"], atol=0)

    # single-process reference on the identical corpus + config
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors

    vocab, seqs = build_corpus_and_vocab()
    sv = SequenceVectors(vocab, layer_size=24, window=3, negative=5,
                         learning_rate=0.05, epochs=8, batch_size=256, seed=7)
    sv.fit_sequences(seqs)

    sep_single = topic_separation(sv.get_word_vector_matrix())
    sep_dist = topic_separation(d0["syn0"])
    # both runs separate the two topics decisively (max possible is 2.0);
    # parameter averaging trades some sharpness for parallelism, so the
    # distributed margin is bounded relative to single-process
    assert sep_single > 1.0, sep_single
    assert sep_dist > 1.0, sep_dist
    assert sep_dist > 0.5 * sep_single, (sep_dist, sep_single)

    # similarity-structure parity: pairwise-cosine matrices of the two
    # runs correlate strongly over all word pairs
    def sim_matrix(m):
        m = m / np.maximum(np.linalg.norm(m, axis=1, keepdims=True), 1e-9)
        s = m @ m.T
        return s[np.triu_indices(len(s), 1)]

    corr = np.corrcoef(sim_matrix(sv.get_word_vector_matrix()),
                       sim_matrix(d0["syn0"]))[0, 1]
    assert corr > 0.9, corr


@pytest.mark.slow
def test_two_process_paragraph_vectors_parity():
    """Distributed doc2vec (the reference's Spark ParagraphVectors
    capability): 2 processes shard DOCUMENTS, word rows are
    parameter-averaged, per-document label rows combined by ownership.
    The result must (a) end bit-identical across replicas — including
    the label rows, which only one process trains, (b) separate the two
    document topics as decisively as single-process training."""
    from tests.pv_corpus import build_docs, build_pv, doc_topic_separation

    outdir, _ = _run_two_workers("multihost_pv_worker.py", "mh_pv_")
    d0 = np.load(os.path.join(outdir, "pv_dist.npz"))
    d1 = np.load(os.path.join(outdir, "pv_dist_1.npz"))
    np.testing.assert_allclose(d0["syn0"], d1["syn0"], atol=0)
    np.testing.assert_allclose(d0["label_vecs"], d1["label_vecs"], atol=0)

    # label rows moved well off their random init (|init| ≤ 0.5/24 ≈
    # 0.021; each row is trained by exactly one owner process and must
    # survive the ownership-weighted combine un-shrunk)
    V = int(d0["n_words"])
    label_rows = d0["syn0"][V:]
    assert np.abs(label_rows).max() > 0.1, np.abs(label_rows).max()

    # single-process reference on the identical corpus + config
    docs = build_docs()
    pv = build_pv(docs).fit()
    ref_vecs = np.stack([pv.get_paragraph_vector(f"DOC_{i}")
                         for i in range(len(docs))])

    sep_single = doc_topic_separation(ref_vecs)
    sep_dist = doc_topic_separation(d0["label_vecs"])
    # doc-vector topic margins are softer than word-vector ones (the
    # label only sees its own doc's words; negatives span both topics):
    # single-process measures ~0.10 on this corpus — require a clearly
    # positive margin and distributed within 2x of single-process
    assert sep_single > 0.04, sep_single
    assert sep_dist > 0.04, sep_dist
    assert sep_dist > 0.5 * sep_single, (sep_dist, sep_single)
