"""Multi-host training parity test — port of the reference's
``TestCompareParameterAveragingSparkVsSingleMachine.java`` (SURVEY.md
§4.5): the SAME net trained (a) across 2 separate processes × 2 CPU
devices on a global mesh via jax.distributed, and (b) in a single process,
must end with matching parameters.

The 2-process run exercises the real multi-host stack: coordinator
bootstrap, Gloo cross-process collectives, host-local→global array
assembly, checkpoint save/restore barrier.
"""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

HERE = os.path.dirname(__file__)


def _run_two_workers(script_name: str, prefix: str, extra_args=()):
    """Launch two worker processes against a fresh coordinator; returns
    (outdir, outputs) after asserting both exit 0."""
    from deeplearning4j_tpu.parallel.multihost import free_port

    port = free_port()
    outdir = tempfile.mkdtemp(prefix=prefix)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script_name),
             f"127.0.0.1:{port}", "2", str(pid), outdir, *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    return outdir, outs


@pytest.mark.slow
def test_two_process_training_matches_single_process():
    outdir, _ = _run_two_workers("multihost_worker.py", "mh_parity_")

    result = np.load(os.path.join(outdir, "multihost_result.npz"))
    assert result["iteration"] == 12  # 3 epochs × 4 global batches
    assert result["n_stats"] > 0  # collect_training_stats plumbing
    assert np.isfinite(result["score"])
    # distributed evaluation merged over BOTH hosts' shards: the count
    # covers the full dataset and both hosts agree exactly
    r1 = np.load(os.path.join(outdir, "multihost_result_1.npz"))
    assert int(result["eval_total"]) == 64  # GLOBAL_BATCH * N_BATCHES
    assert float(result["eval_accuracy"]) == float(r1["eval_accuracy"])
    assert int(r1["eval_total"]) == 64

    # single-process reference: same net, same global batches, 3 epochs
    from tests.multihost_model import build_net, global_batches

    net = build_net()
    it = global_batches()
    for _ in range(3):
        net._fit_one_epoch(it)
    single = net.params_flat()

    multi = result["params"]
    assert multi.shape == single.shape
    # fp32 CPU vs fp32 Gloo-reduced: tolerances cover reduction-order noise
    np.testing.assert_allclose(multi, single, atol=1e-4, rtol=1e-3)
    # and training moved the params (not trivially passing on init state)
    init = build_net().params_flat()
    assert np.abs(single - init).max() > 1e-3


@pytest.mark.slow
def test_two_process_compressed_gradient_training():
    """SharedTrainingMaster across 2 processes: threshold-encoded updates
    cross hosts via the gathered messages; both processes converge and
    END WITH IDENTICAL PARAMETERS (the decode is deterministic and
    symmetric — the reference's SharedTraining consistency property)."""
    outdir, _ = _run_two_workers("multihost_shared_worker.py", "mh_shared_")

    r0 = np.load(os.path.join(outdir, "shared_result_0.npz"))
    r1 = np.load(os.path.join(outdir, "shared_result_1.npz"))
    assert r0["last"] < 0.6 * r0["first"], (r0["first"], r0["last"])
    # bit-identical replicas across hosts
    np.testing.assert_allclose(r0["params"], r1["params"], atol=0)


@pytest.mark.slow
def test_two_process_orbax_cooperative_checkpoint():
    """Cooperative Orbax save from a 2-process global mesh + restore onto
    a placed template (OrbaxModelSerializer's multi-host contract)."""
    outdir, _ = _run_two_workers("multihost_orbax_worker.py", "mh_orbax_")
    for pid in range(2):
        assert os.path.exists(os.path.join(outdir, f"orbax_ok_{pid}"))
