"""Forensic observability (obs/flight.py, obs/cost.py, serving/rtrace.py):
flight recorder + black-box dumps, per-request serving traces,
hardware-efficiency (MFU) profiling, and the hardening satellites
(server shutdown, registry concurrency, pad-waste metric).

The three ISSUE-7 acceptance drills live here as tier-1 tests:

1. a deliberately diverged fit (fault_injection NaN drill with
   ``max_consecutive_bad_steps`` armed) leaves a READABLE flight dump
   whose last events include the NaN-skips and the divergence trip;
2. a served request with tracing enabled returns a stage timeline whose
   durations sum to (within) the measured end-to-end latency;
3. MFU/FLOPs gauges appear in Prometheus exposition for both a bundled
   fit and a warmed serving engine.
"""

import gc
import http.client
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import cost as obs_cost
from deeplearning4j_tpu.obs.exporter import MetricsServer
from deeplearning4j_tpu.obs.flight import (
    FlightRecorder,
    FlightRecorderListener,
    default_flight_recorder,
    find_dump,
    format_dump,
    install_signal_dump,
)
from deeplearning4j_tpu.obs.metrics import MetricsListener, MetricsRegistry
from deeplearning4j_tpu.serving import (
    BucketPolicy,
    InferenceEngine,
    InferenceServer,
)
from deeplearning4j_tpu.train.faults import (
    FaultPolicy,
    TrainingDivergedError,
    fault_injection,
    save_checkpoint,
)
from deeplearning4j_tpu.updaters import Adam


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    """Same heap-pressure hygiene as tests/test_serving.py: drop this
    module's executables when done."""
    yield
    gc.collect()
    jax.clear_caches()


@pytest.fixture(autouse=True)
def _reset_default_recorder():
    """The default flight recorder is process-global (the fault guard
    and batcher record into it); restore its dump_dir and drop this
    test's events so later tests (incl. the fault-tolerance suite's own
    divergence drills) never auto-dump into a deleted tmpdir."""
    rec = default_flight_recorder()
    prev_dir = rec.dump_dir
    yield
    rec.dump_dir = prev_dir
    rec.clear()


def _batches(n, b=8, d=12, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return [
        DataSet(rng.standard_normal((b, d)).astype(np.float32),
                np.eye(c, dtype=np.float32)[rng.integers(0, c, b)])
        for _ in range(n)
    ]


def _mlp(k=1, fault_policy=None, seed=7):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .steps_per_call(k))
    if fault_policy is not None:
        b = b.fault_policy(fault_policy)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def _serving_net(seed=7, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# flight recorder core
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bounds_and_drop_accounting(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("step", iteration=i)
        assert len(rec) == 4
        assert rec.recorded_total == 10
        evs = rec.events()
        assert [e["iteration"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == [6, 7, 8, 9]
        snap = rec.snapshot()
        assert snap["dropped"] == 6
        assert rec.events(last=2)[0]["iteration"] == 8

    def test_dump_roundtrip_and_overwrite(self, tmp_path):
        rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        assert rec.dump() is None  # empty ring: no misleading black box
        rec.record("a", x=1)
        p1 = rec.dump(reason="first")
        rec.record("b", y=2.5)
        p2 = rec.dump(reason="second")
        assert p1 == p2  # one file per process, atomically overwritten
        body = json.load(open(p2))
        assert body["reason"] == "second"
        assert [e["kind"] for e in body["events"]] == ["a", "b"]
        assert body["events"][1]["y"] == 2.5
        # the reader helpers resolve and render it
        assert find_dump(str(tmp_path)) == p2
        text = format_dump(body)
        assert "b" in text and "y=2.5" in text

    def test_non_jsonable_values_coerced(self, tmp_path):
        rec = FlightRecorder(dump_dir=str(tmp_path))
        rec.record("step", loss=np.float32(1.5), it=np.int64(3),
                    weird=object())
        body = json.load(open(rec.dump()))
        ev = body["events"][0]
        assert ev["loss"] == 1.5 and ev["it"] == 3
        assert isinstance(ev["weird"], str)

    def test_concurrent_record(self):
        rec = FlightRecorder(capacity=10_000)
        n_threads, per = 8, 500

        def writer(t):
            for i in range(per):
                rec.record("w", thread=t, i=i)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert rec.recorded_total == n_threads * per
        seqs = [e["seq"] for e in rec.events()]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_find_dump_missing(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            find_dump(str(tmp_path))


# ---------------------------------------------------------------------------
# ACCEPTANCE DRILL 1: diverged fit leaves a readable black box
# ---------------------------------------------------------------------------
class TestDivergenceDrill:
    def test_nan_drill_dump(self, tmp_path):
        net = _mlp(fault_policy=FaultPolicy(
            skip_nonfinite=True, max_consecutive_bad_steps=2))
        net.add_listeners(FlightRecorderListener(directory=str(tmp_path),
                                                 loss_frequency=1))
        batches = _batches(10)
        with fault_injection(nan_grad_steps=[4, 5, 6]):
            with pytest.raises(TrainingDivergedError):
                net.fit(ExistingDataSetIterator(batches), epochs=1)
        path = find_dump(str(tmp_path))
        body = json.load(open(path))  # readable == parseable JSON
        kinds = [e["kind"] for e in body["events"]]
        # the LAST events tell the postmortem story: the NaN-skip
        # streak, the divergence trip, the dying fit
        tail = kinds[-6:]
        assert "nan_skip" in tail
        assert "divergence_trip" in tail
        assert kinds[-1] == "fit_exception"
        assert body["events"][-1]["error"] == "TrainingDivergedError"
        trip = [e for e in body["events"] if e["kind"] == "divergence_trip"]
        assert trip[-1]["consec"] == 2 and trip[-1]["limit"] == 2
        # healthy steps before the streak carried their losses
        losses = [e["loss"] for e in body["events"]
                  if e["kind"] == "step" and "loss" in e]
        assert losses and all(np.isfinite(losses[:3]))
        # the dump is the superset written at fit exit
        assert body["reason"] == "fit_exception"

    def test_divergence_dumps_even_without_listener(self, tmp_path):
        """check_fault_state dumps BEFORE raising whenever the default
        recorder has a dump_dir — a caller that swallows the error still
        leaves the postmortem."""
        rec = default_flight_recorder()
        rec.dump_dir = str(tmp_path)
        net = _mlp(fault_policy=FaultPolicy(
            skip_nonfinite=True, max_consecutive_bad_steps=1), seed=21)
        with fault_injection(nan_grad_steps=[2, 3]):
            try:
                net.fit(ExistingDataSetIterator(_batches(6, seed=3)),
                        epochs=1)
            except TrainingDivergedError:
                pass  # the swallowing caller
        body = json.load(open(find_dump(str(tmp_path))))
        assert any(e["kind"] == "divergence_trip" for e in body["events"])

    def test_transient_nan_skip_visible_under_bundling(self):
        """The per-dispatch tripwire only sees END-of-bundle consec: a
        NaN step that recovers before the bundle boundary checks in with
        consec==0, and only the bad_count delta against the owner's
        previous check reveals it. The black box must still get it."""
        rec = default_flight_recorder()
        before = rec.recorded_total
        net = _mlp(k=4, fault_policy=FaultPolicy(
            skip_nonfinite=True, max_consecutive_bad_steps=3), seed=33)
        with fault_injection(nan_grad_steps=[1]):
            net.fit(ExistingDataSetIterator(_batches(8, seed=5)), epochs=1)
        skips = [e for e in rec.events()
                 if e["seq"] >= before and e["kind"] == "nan_skip"]
        assert skips, "mid-bundle transient NaN left no nan_skip event"
        assert skips[0]["consec"] == 0 and skips[0]["bad_count"] >= 1
        # and ONE transient must not spam every later clean check
        assert len(skips) == 1


# ---------------------------------------------------------------------------
# flight listener behavior
# ---------------------------------------------------------------------------
class TestFlightRecorderListener:
    def test_clean_fit_records_and_dumps(self, tmp_path):
        rec = FlightRecorder(capacity=512)
        net = _mlp(k=4)
        net.add_listeners(FlightRecorderListener(
            recorder=rec, directory=str(tmp_path), loss_frequency=4))
        net.fit(ExistingDataSetIterator(_batches(8)), epochs=2)
        kinds = [e["kind"] for e in rec.events()]
        assert kinds.count("epoch_start") == 2
        assert kinds.count("epoch_end") == 2
        assert kinds[-1] == "fit_end"
        bundles = [e for e in rec.events() if e["kind"] == "bundle"]
        assert len(bundles) == 4  # 8 batches / K=4 per epoch x 2 epochs
        assert all(b["k"] == 4 for b in bundles)
        # every bundle spans a loss_frequency=4 hit → loss attached
        assert all("loss" in b and np.isfinite(b["loss"]) for b in bundles)
        # clean exit still leaves the black box on disk
        body = json.load(open(find_dump(str(tmp_path))))
        assert body["reason"] == "fit_end"

    def test_off_frequency_bundles_skip_the_fetch(self):
        """loss sampling respects the once-per-bundle discipline: with
        loss_frequency beyond the run length no scores are fetched at
        all (fetch_count is observable on BundleScores)."""
        from deeplearning4j_tpu.train import pipeline as _pipeline

        rec = FlightRecorder()
        net = _mlp(k=4, seed=9)
        net.add_listeners(FlightRecorderListener(recorder=rec,
                                                 loss_frequency=10_000))
        before = _pipeline._host_fetches
        net.fit(ExistingDataSetIterator(_batches(8, seed=2)), epochs=1)
        assert _pipeline._host_fetches == before  # zero score fetches
        bundles = [e for e in rec.events() if e["kind"] == "bundle"]
        assert len(bundles) == 2 and all("loss" not in b for b in bundles)

    def test_checkpoint_events(self, tmp_path):
        from deeplearning4j_tpu.train.faults import load_latest_valid

        rec = default_flight_recorder()
        net = _mlp(seed=11)
        net.fit(ExistingDataSetIterator(_batches(2)), epochs=1)
        path = save_checkpoint(net, str(tmp_path))
        load_latest_valid(str(tmp_path))
        kinds = [e["kind"] for e in rec.events()]
        assert "checkpoint_write" in kinds and "checkpoint_load" in kinds
        w = [e for e in rec.events() if e["kind"] == "checkpoint_write"][-1]
        assert w["path"] == path

    def test_sigterm_dump_chains_previous_handler(self, tmp_path):
        rec = default_flight_recorder()
        rec.dump_dir = str(tmp_path)
        rec.record("before_signal")
        hits = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            uninstall = install_signal_dump()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5
            while not hits and time.monotonic() < deadline:
                time.sleep(0.01)
            assert hits == [signal.SIGTERM]  # chained handler ran
            body = json.load(open(rec.dump_path()))
            assert body["reason"] == f"signal_{int(signal.SIGTERM)}"
            assert any(e["kind"] == "signal" for e in body["events"])
            uninstall()
            assert signal.getsignal(signal.SIGTERM) is not None
        finally:
            signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# ACCEPTANCE DRILL 2: traced request timeline
# ---------------------------------------------------------------------------
class TestRequestTraceDrill:
    def test_traced_request_timeline_sums(self):
        net = _serving_net()
        engine = InferenceEngine(net,
                                 buckets=BucketPolicy(batch_buckets=[4, 8]))
        engine.warmup()
        server = InferenceServer(engine, port=0, max_wait_ms=1.0).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            x = np.random.default_rng(0).standard_normal((3, 4)).astype(
                np.float32)
            t0 = time.perf_counter()
            conn.request("POST", "/predict",
                         json.dumps({"inputs": x.tolist(), "trace": True}))
            resp = conn.getresponse()
            body = json.loads(resp.read())
            wall_ms = (time.perf_counter() - t0) * 1e3
            assert resp.status == 200
            tl = body["trace"]
            names = [s["stage"] for s in tl["stages"]]
            assert names == ["queue", "assembly", "forward", "slice",
                             "respond"]
            # the intervals partition enqueue→respond: they sum exactly
            # to the reported total, and the total sits inside the
            # measured end-to-end latency (which adds HTTP + JSON time)
            ssum = sum(s["ms"] for s in tl["stages"])
            assert ssum == pytest.approx(tl["total_ms"], abs=0.01)
            assert tl["total_ms"] <= wall_ms + 0.01
            assert tl["bucket"] == 4
            assert tl["rows"] == 3 and tl["batch_rows_real"] == 3
            assert tl["batch_rows_padded"] == 4
            assert tl["pad_waste"] == pytest.approx(0.25)
            assert tl["model_version"] == 0
            # the same timeline landed in the /trace window
            conn.request("GET", "/trace")
            tb = json.loads(conn.getresponse().read())
            assert tb["recorded_total"] >= 1
            assert tb["traces"][-1]["total_ms"] > 0
            assert tb["pad_waste"]["4"]["real"] >= 3
            conn.close()
        finally:
            server.shutdown()

    def test_per_request_opt_in_when_server_tracing_off(self):
        engine = InferenceEngine(_serving_net(seed=8),
                                 buckets=BucketPolicy(batch_buckets=[4]))
        engine.warmup()
        server = InferenceServer(engine, port=0, max_wait_ms=1.0,
                                 trace_requests=False).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            x = [[0.0, 0.0, 0.0, 0.0]]
            conn.request("POST", "/predict", json.dumps({"inputs": x}))
            body = json.loads(conn.getresponse().read())
            assert "trace" not in body
            assert len(server.traces) == 0  # nothing sampled when off
            conn.request("POST", "/predict",
                         json.dumps({"inputs": x, "trace": True}))
            body = json.loads(conn.getresponse().read())
            assert body["trace"]["total_ms"] > 0  # opt-in still works
            conn.close()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# pad-waste metric (satellite)
# ---------------------------------------------------------------------------
class TestPadWasteMetric:
    def test_engine_records_real_vs_padded(self):
        engine = InferenceEngine(_serving_net(seed=5),
                                 buckets=BucketPolicy(batch_buckets=[4, 8]))
        engine.warmup()  # warmup rows are exact-fit: zero waste
        waste0 = engine.metrics.pad_waste()
        assert all(v["padded"] == 0 for v in waste0.values())
        engine.infer(np.zeros((3, 4), np.float32))
        engine.infer(np.zeros((5, 4), np.float32))
        waste = engine.metrics.pad_waste()
        assert waste[4]["padded"] == waste0[4]["padded"] + 1
        assert waste[8]["padded"] == waste0[8]["padded"] + 3
        snap = engine.metrics.snapshot()
        assert snap["pad_waste"]["8"]["waste_ratio"] == pytest.approx(
            waste[8]["padded"] / (waste[8]["padded"] + waste[8]["real"]),
            abs=1e-4)
        text = engine.metrics.prometheus_text()
        assert "serving_padded_samples_total" in text
        assert "serving_real_samples_total" in text


# ---------------------------------------------------------------------------
# ACCEPTANCE DRILL 3: MFU / FLOPs gauges
# ---------------------------------------------------------------------------
class TestHardwareEfficiency:
    def test_bundled_fit_mfu_gauges(self):
        reg = MetricsRegistry()
        net = _mlp(k=4, seed=13)
        net.add_listeners(MetricsListener(registry=reg, frequency=4))
        ds = _batches(1, seed=5)[0]
        out = obs_cost.publish_train_cost(net, ds, steps_per_call=4,
                                          registry=reg)
        assert out["flops"] > 0 and out["flops_per_step"] > 0
        assert out["steps_per_call"] == 4
        net.fit(ExistingDataSetIterator(_batches(16, seed=5)), epochs=1)
        text = reg.prometheus_text()
        assert 'step_flops{k="4",step="train"}' in text
        assert 'step_bytes_accessed{k="4",step="train"}' in text
        assert 'model_flops_utilization{step="train"}' in text
        assert 'step_bytes_per_sec{step="train"}' in text
        # the fit published steps/sec, so scraped MFU is live and > 0
        mfu = reg.get("model_flops_utilization",
                      {"step": "train"}).value()
        assert 0 < mfu < 1

    def test_warmed_engine_mfu_gauges(self):
        engine = InferenceEngine(_serving_net(seed=6),
                                 buckets=BucketPolicy(batch_buckets=[4, 8]))
        engine.warmup()
        out = engine.publish_cost_metrics()
        assert out["bucket"] == 8
        assert out["flops"] > 0 and out["flops_per_example"] > 0
        reg = engine.metrics.registry
        text = reg.prometheus_text()
        assert 'model_flops_utilization{step="serving"}' in text
        assert 'step_flops{bucket="8",step="serving"}' in text
        # MFU is a scrape-to-scrape rate: baseline scrape, serve work,
        # second scrape shows utilization > 0
        gauge = reg.get("model_flops_utilization", {"step": "serving"})
        bps = reg.get("step_bytes_per_sec", {"step": "serving"})
        gauge.value()  # baseline
        for _ in range(3):
            engine.infer(np.zeros((8, 4), np.float32))
        time.sleep(obs_cost._RATE_MIN_WINDOW_S + 0.05)
        # a scrape evaluates BOTH gauges off the one shared rate
        # closure — the second must not read a consumed ~0 delta
        assert bps.value() > 0
        assert gauge.value() > 0

    def test_peak_flops_env_override(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "2.5e12")
        pk = obs_cost.hardware_peak_flops()
        assert pk["per_device"] == 2.5e12
        assert pk["source"] == "env:DL4J_TPU_PEAK_FLOPS"
        monkeypatch.delenv("DL4J_TPU_PEAK_FLOPS")
        pk = obs_cost.hardware_peak_flops()
        assert pk["peak_flops"] > 0 and "source" in pk

    def test_train_cost_does_not_perturb_training(self):
        """The analysis lowers with ShapeDtypeStructs — params and the
        rng stream must be untouched, so the fit after a cost report is
        bit-identical to one without it."""
        batches = _batches(6, seed=17)

        def run(with_cost):
            net = _mlp(k=1, seed=19)
            if with_cost:
                obs_cost.train_step_analysis(net, batches[0])
            net.fit(ExistingDataSetIterator(batches), epochs=1)
            return jax.tree_util.tree_leaves(net.params_)

        a, b = run(False), run(True)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_profiler_capture_and_busy_guard(self, tmp_path):
        res = obs_cost.profiler_capture(30, log_dir=str(tmp_path))
        assert res["ms"] == 30.0 and os.path.isdir(res["log_dir"])
        errs = []

        def long_capture():
            try:
                obs_cost.profiler_capture(1500)
            except obs_cost.ProfilerBusyError as e:
                errs.append(e)

        t = threading.Thread(target=long_capture)
        t.start()
        time.sleep(0.2)
        with pytest.raises(obs_cost.ProfilerBusyError):
            obs_cost.profiler_capture(30)
        t.join()
        assert not errs  # the long capture itself succeeded


# ---------------------------------------------------------------------------
# debug endpoints
# ---------------------------------------------------------------------------
class TestDebugEndpoints:
    def test_metrics_server_flight_and_profile(self):
        rec = default_flight_recorder()
        rec.record("endpoint_marker", tag="metrics-server")
        server = MetricsServer(registry=MetricsRegistry(), port=0).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            conn.request("GET", "/debug/flight")
            body = json.loads(conn.getresponse().read())
            assert any(e["kind"] == "endpoint_marker"
                       for e in body["events"])
            conn.request("GET", "/debug/profile?ms=20")
            resp = conn.getresponse()
            prof = json.loads(resp.read())
            assert resp.status == 200 and os.path.isdir(prof["log_dir"])
            conn.close()
        finally:
            server.shutdown()

    def test_inference_server_flight_endpoint(self):
        engine = InferenceEngine(_serving_net(seed=4),
                                 buckets=BucketPolicy(batch_buckets=[4]))
        server = InferenceServer(engine, port=0).start()
        try:
            default_flight_recorder().record("endpoint_marker",
                                             tag="inference-server")
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            conn.request("GET", "/debug/flight")
            body = json.loads(conn.getresponse().read())
            assert any(e["kind"] == "endpoint_marker"
                       for e in body["events"])
            conn.close()
        finally:
            server.shutdown()

    def test_cli_flight_dump_reader(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import flight_dump_main

        rec = FlightRecorder(dump_dir=str(tmp_path))
        rec.record("nan_skip", consec=2)
        rec.dump(reason="drill")
        assert flight_dump_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "nan_skip" in out and "reason=drill" in out
        assert flight_dump_main([str(tmp_path), "--json"]) == 0
        assert json.loads(
            capsys.readouterr().out)["events"][0]["kind"] == "nan_skip"
        assert flight_dump_main([str(tmp_path / "nope")]) == 1


# ---------------------------------------------------------------------------
# shutdown hardening (satellite)
# ---------------------------------------------------------------------------
class TestServerShutdownHardening:
    def _no_hang(self, fn, timeout=5.0):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join(timeout)
        assert not t.is_alive(), "shutdown hung"

    def test_metrics_server_shutdown_never_started(self):
        server = MetricsServer(registry=MetricsRegistry(), port=0)
        self._no_hang(server.shutdown)  # BaseServer.shutdown would hang

    def test_metrics_server_double_shutdown(self):
        server = MetricsServer(registry=MetricsRegistry(), port=0).start()
        server.shutdown()
        self._no_hang(server.shutdown)

    def test_metrics_server_port_released(self):
        server = MetricsServer(registry=MetricsRegistry(), port=0).start()
        port = server.port
        server.shutdown()
        again = MetricsServer(registry=MetricsRegistry(), port=port)
        assert again.port == port
        again.shutdown()

    def test_metrics_server_scrape_during_shutdown(self):
        """Scrapers racing shutdown get a response or a clean socket
        error — never a hung server or a dead handler thread wedging
        close."""
        server = MetricsServer(registry=MetricsRegistry(), port=0).start()
        port = server.port
        stop = threading.Event()
        errors = []

        def scraper():
            while not stop.is_set():
                try:
                    conn = http.client.HTTPConnection("127.0.0.1", port,
                                                      timeout=1)
                    conn.request("GET", "/metrics")
                    conn.getresponse().read()
                    conn.close()
                except OSError:
                    pass  # expected once the socket closes
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        threads = [threading.Thread(target=scraper) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        self._no_hang(server.shutdown)
        stop.set()
        for t in threads:
            t.join(timeout=3)
        assert not errors

    def test_inference_server_double_shutdown(self):
        engine = InferenceEngine(_serving_net(seed=3),
                                 buckets=BucketPolicy(batch_buckets=[4]))
        server = InferenceServer(engine, port=0).start()
        server.shutdown()
        self._no_hang(server.shutdown)

    def test_inference_server_shutdown_never_started(self):
        engine = InferenceEngine(_serving_net(seed=2),
                                 buckets=BucketPolicy(batch_buckets=[4]))
        server = InferenceServer(engine, port=0)
        self._no_hang(server.shutdown)


# ---------------------------------------------------------------------------
# registry concurrency (satellite)
# ---------------------------------------------------------------------------
class TestRegistryConcurrency:
    def test_writers_vs_scraper(self):
        """N writer threads hammering one counter + one histogram while
        readers scrape: no lost increments, no torn quantiles (every
        scraped quantile lies within the observed value range), no
        exceptions."""
        reg = MetricsRegistry()
        counter = reg.counter("c_total")
        hist = reg.histogram("h_seconds", ring_size=256)
        n_threads, per = 6, 400
        lo, hi = 0.5, 2.5
        stop = threading.Event()
        errors = []

        def writer(t):
            rng = np.random.default_rng(t)
            for _ in range(per):
                counter.inc()
                hist.observe(float(rng.uniform(lo, hi)))

        def reader():
            while not stop.is_set():
                try:
                    text = reg.prometheus_text()
                    assert "c_total" in text
                    snap = reg.snapshot()
                    h = snap["h_seconds"]
                    for q in ("p50", "p90", "p99"):
                        if h[q] is not None:
                            assert lo <= h[q] <= hi, (q, h[q])
                    q99 = hist.quantile(0.99)
                    if q99 is not None:
                        assert lo <= q99 <= hi
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return

        writers = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        readers = [threading.Thread(target=reader) for _ in range(2)]
        for t in readers + writers:
            t.start()
        for t in writers:
            t.join()
        stop.set()
        for t in readers:
            t.join(timeout=5)
        assert not errors
        assert counter.value() == n_threads * per  # no lost increments
        assert hist.count == n_threads * per
