"""Load generation + adaptive capacity tests (loadgen/ package).

Plan compilation is deterministic (same seed → identical fingerprint,
serde roundtrips preserve identity, overrides produce a stream that
carries its EFFECTIVE seed), validation fails fast with typed
messages, the injected clocks honor the forward-only/compression
contracts, the runner replays a compiled stream against a real
DynamicBatcher with typed outcomes and tick-aligned controller pumping,
and each capacity controller closes its observe→act loop: DeadlineTuner
shrink/relax/bucket-learning (zero steady-state retraces,
compile-counter-asserted), SlotScaler with the memory-estimator gate,
TenantDemoter demote + quiet-restore against a real ModelRouter (the
``tenant_demoted`` alert fires off the gauge it sets), ModelPrewarmer
forecast-driven prewarm/evict, and the ControllerHub containing
actuator faults. The oscillation chaos drill itself runs in
test_chaos.py's fast-drill matrix."""

import json
import time

import numpy as np
import pytest

from deeplearning4j_tpu.loadgen import (
    BUILTIN_PLANS,
    ControllerHub,
    DeadlineTuner,
    LoadPlan,
    LoadRunner,
    ModelPrewarmer,
    SimClock,
    SlotScaler,
    TenantDemoter,
    VirtualClock,
    batcher_target,
    diurnal_flash_plan,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import flight as _flight
from deeplearning4j_tpu.obs.alerts import AlertEvaluator
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.obs.slo import default_rules
from deeplearning4j_tpu.serving import BucketPolicy, InferenceEngine
from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher,
    make_dispatcher,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics


def _steady_plan(duration_s=2.0, rps=40.0, seed=1, tick_s=0.5):
    return LoadPlan(
        [{"process": "poisson", "rps": rps}],
        [{"name": "steady", "kind": "predict",
          "rows": {"dist": "lognormal", "median": 2, "sigma": 0.5,
                   "max": 8}}],
        name="test-steady", seed=seed, duration_s=duration_s,
        tick_s=tick_s)


def _net(seed=7, n_in=4):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _events_since(seq, kinds=None):
    return [e for e in _flight.default_flight_recorder().events()
            if e["seq"] >= seq and (kinds is None or e["kind"] in kinds)]


class _Verdict:
    def __init__(self, status="healthy", firing=()):
        self.status = status
        self.firing = [{"name": n} for n in firing]


def _hub(controllers=(), registry=None):
    return ControllerHub(AlertEvaluator([], registry=registry,
                                        min_tick_interval=0.0),
                         controllers)


# ---------------------------------------------------------------------------
# plan compilation: determinism, identity, validation
# ---------------------------------------------------------------------------
class TestLoadPlan:
    def test_same_seed_identical_stream(self):
        plan = diurnal_flash_plan(duration_s=20.0)
        s1, s2 = plan.compile(), plan.compile()
        assert s1.fingerprint() == s2.fingerprint()
        assert [r.key() for r in s1] == [r.key() for r in s2]

    def test_seed_override_changes_stream_and_identity(self):
        plan = _steady_plan(seed=1)
        base = plan.compile()
        over = plan.compile(seed=2)
        assert over.fingerprint() != base.fingerprint()
        # the derived stream must CARRY the effective seed — replaying
        # "seed 2" twice matches, and the original plan is untouched
        assert over.plan.seed == 2 and plan.seed == 1
        assert over.fingerprint() == plan.compile(seed=2).fingerprint()

    def test_duration_override_carried(self):
        plan = _steady_plan(duration_s=4.0)
        short = plan.compile(duration_s=1.0)
        assert short.plan.duration_s == 1.0
        assert short.duration_s() <= 1.0
        assert short.fingerprint() != plan.compile().fingerprint()

    def test_serde_roundtrip_preserves_stream(self):
        plan = diurnal_flash_plan(duration_s=15.0)
        clone = LoadPlan.from_json(plan.to_json())
        assert clone.compile().fingerprint() == plan.compile().fingerprint()

    def test_requests_sorted_with_stable_rids(self):
        s = _steady_plan().compile()
        ts = [r.t for r in s]
        assert ts == sorted(ts)
        assert [r.rid for r in s] == list(range(len(s)))

    def test_adversarial_patterns_shape_requests(self):
        plan = LoadPlan(
            [{"process": "poisson", "rps": 30.0}],
            [{"name": "spam", "adversarial": "one_token_spam"},
             {"name": "flood", "kind": "predict",
              "adversarial": "deadline_flood", "deadline_ms": 1.0,
              "rows": {"dist": "const", "value": 1}}],
            name="adv", seed=3, duration_s=3.0)
        reqs = list(plan.compile())
        spam = [r for r in reqs if r.tenant == "spam"]
        flood = [r for r in reqs if r.tenant == "flood"]
        assert spam and all(r.kind == "generate" and r.max_new == 1
                            for r in spam)
        assert flood and all(r.deadline_ms == 1.0 for r in flood)

    def test_flash_crowd_shows_in_forecast(self):
        plan = diurnal_flash_plan(duration_s=60.0)
        # flash lands at 0.55 * duration — the forecast must spike there
        assert plan.forecast(33.0) > 2 * plan.forecast(5.0)

    @pytest.mark.parametrize("mutation, match", [
        ({"arrivals": [{"process": "warp"}]}, "unknown process"),
        ({"arrivals": []}, "at least one arrival"),
        ({"tenants": []}, "at least one tenant"),
        ({"tenants": [{"name": "t", "kind": "teleport"}]},
         "unknown kind"),
        ({"duration_s": -1.0}, "must be > 0"),
    ])
    def test_validation_fails_fast(self, mutation, match):
        body = {"arrivals": [{"process": "poisson", "rps": 1.0}],
                "tenants": [{"name": "t", "kind": "predict"}],
                "name": "bad", "duration_s": 5.0}
        body.update(mutation)
        with pytest.raises(ValueError, match=match):
            LoadPlan.from_dict(body).compile()

    def test_builtins_compile(self):
        for name, factory in BUILTIN_PLANS.items():
            s = factory(duration_s=5.0).compile()
            assert len(s) > 0, name


# ---------------------------------------------------------------------------
# injected clocks
# ---------------------------------------------------------------------------
class TestClocks:
    def test_virtual_clock_forward_only(self):
        c = VirtualClock()
        assert c() == 0.0
        c.advance(2.5)
        c.set(4.0)
        assert c.now() == 4.0
        with pytest.raises(ValueError):
            c.advance(-1.0)
        with pytest.raises(ValueError):
            c.set(3.0)

    def test_sim_clock_compression(self):
        wall = [100.0]
        c = SimClock(compression=60.0, wall=lambda: wall[0])
        assert c.now() == 0.0
        wall[0] += 0.5  # half a wall second = 30 simulated seconds
        assert c.now() == pytest.approx(30.0)
        assert c.wall_remaining(60.0) == pytest.approx(0.5)
        assert c.sleep_until(10.0) is True  # already past: no block

    def test_sim_clock_rejects_bad_compression(self):
        with pytest.raises(ValueError):
            SimClock(compression=0.0)


# ---------------------------------------------------------------------------
# runner: replay against a real batcher, typed outcomes, tick pumping
# ---------------------------------------------------------------------------
class TestLoadRunner:
    def test_replay_through_real_batcher(self):
        met = ServingMetrics()
        batcher = DynamicBatcher(
            make_dispatcher(lambda x, mask=None: np.asarray(x) * 2.0,
                            metrics=met),
            batch_limit=16, max_wait_ms=2.0, queue_limit=256,
            metrics=met)
        try:
            s = _steady_plan(duration_s=2.0, rps=40.0).compile()
            report = LoadRunner(s, batcher_target(batcher, (4,)),
                                compression=20.0).run()
        finally:
            batcher.shutdown(drain=False)
        assert report.submitted == len(s)
        assert report.ok() > 0.9 * len(s)
        assert report.p(0.99) > 0.0
        assert "steady" in report.by_tenant

    def test_typed_submit_rejects_become_outcomes(self):
        class TeapotError(Exception):
            pass

        def target(req):
            raise TeapotError("short and stout")

        report = LoadRunner(_steady_plan(duration_s=1.0).compile(),
                            target, compression=50.0).run()
        assert report.outcomes.get("TeapotError", 0) == report.submitted
        assert report.ok() == 0

    def test_on_tick_pumped_at_tick_boundaries(self):
        ticks = []
        LoadRunner(_steady_plan(duration_s=2.0, tick_s=0.5).compile(),
                   lambda req: (lambda: None), compression=50.0,
                   on_tick=ticks.append).run()
        assert ticks == sorted(ticks)
        # every boundary in (0, duration + tick] observed exactly once
        assert len(ticks) >= 4 and len(set(ticks)) == len(ticks)

    def test_steady_state_quantile_skips_warm_in(self):
        s = _steady_plan(duration_s=1.0).compile()
        report = LoadRunner(s, lambda req: (lambda: None),
                            compression=50.0).run()
        report.timed_latencies = [(0.1, 9.0), (0.2, 9.0), (6.0, 0.5),
                                  (7.0, 0.5)]
        assert report.p_steady(0.99, skip_s=5.0) == 0.5

    def test_replay_records_flight_events(self):
        seq = _flight.default_flight_recorder().recorded_total
        s = _steady_plan(duration_s=1.0).compile()
        LoadRunner(s, lambda req: (lambda: None), compression=50.0).run()
        evs = _events_since(seq, {"loadgen_start", "loadgen_done"})
        assert [e["kind"] for e in evs] == ["loadgen_start",
                                           "loadgen_done"]
        assert evs[0]["fingerprint"] == s.fingerprint()[:16]


# ---------------------------------------------------------------------------
# DeadlineTuner: breach → shrink, clear → relax, calm → bucket learning
# ---------------------------------------------------------------------------
class TestDeadlineTuner:
    def _breach_evaluator(self):
        reg = MetricsRegistry()
        gauge = reg.gauge("serving_latency_p99_ms", "test signal")
        ev = AlertEvaluator(default_rules(latency_slo_ms=100.0),
                            registry=reg, min_tick_interval=0.0)
        return ev, gauge

    def test_shrink_on_breach_relax_on_clear(self):
        ev, gauge = self._breach_evaluator()
        batcher = DynamicBatcher(lambda batch: None, max_wait_ms=8.0)
        try:
            tuner = DeadlineTuner(batcher, cooldown_s=5.0,
                                  min_rows=10 ** 9)
            hub = ControllerHub(ev, [tuner])
            seq = _flight.default_flight_recorder().recorded_total
            gauge.set(400.0)
            hub.tick(0.0)           # pending (for_s hysteresis)
            hub.tick(3.0)           # firing → shrink 8 → 4
            assert batcher.max_wait_s == pytest.approx(4e-3)
            hub.tick(4.0)           # cooldown suppresses the flap
            assert batcher.max_wait_s == pytest.approx(4e-3)
            hub.tick(9.0)           # still breached → 4 → 2
            assert batcher.max_wait_s == pytest.approx(2e-3)
            gauge.set(10.0)
            # below threshold but resolve_s hysteresis keeps it FIRING
            # — one more shrink, exactly the flap suppression contract
            hub.tick(14.0)
            assert batcher.max_wait_s == pytest.approx(1e-3)
            hub.tick(25.0)          # resolved → relax 1 → 1.5
            assert batcher.max_wait_s == pytest.approx(1.5e-3)
            for now in (31.0, 37.0, 43.0, 49.0, 55.0):
                hub.tick(now)
            # relaxed back to the configured deadline, never past it
            assert batcher.max_wait_s == pytest.approx(8e-3)
            evs = _events_since(seq, {"controller_retune"})
            assert {e["action"] for e in evs} == {"deadline_shrink",
                                                 "deadline_relax"}
            assert all("verdict" in e for e in evs)
            shrinks = [e for e in evs if e["action"] == "deadline_shrink"]
            assert shrinks[0]["alerts"] == ["serving_latency_slo_breach"]
        finally:
            batcher.shutdown(drain=False)

    def test_calm_traffic_learns_buckets_with_zero_retraces(self):
        met = ServingMetrics()
        engine = InferenceEngine(
            _net(), buckets=BucketPolicy(batch_buckets=[32],
                                         max_batch=32), metrics=met)
        engine.warmup()
        # the observed mix: small dispatches a [32]-only policy wastes
        rng = np.random.default_rng(0)
        for _ in range(64):
            met.record_dispatch(32, int(rng.integers(1, 5)))
        batcher = DynamicBatcher(lambda batch: None, max_wait_ms=5.0)
        try:
            tuner = DeadlineTuner(batcher, engine=engine, min_rows=32,
                                  cooldown_s=0.0)
            hub = ControllerHub(
                AlertEvaluator([], registry=met.registry,
                               min_tick_interval=0.0), [tuner])
            seq = _flight.default_flight_recorder().recorded_total
            c0 = engine.compile_count
            hub.tick(0.0)
            learned = list(engine.buckets.batch_buckets)
            assert learned != [32] and learned[-1] == 32
            evs = _events_since(seq, {"controller_retune"})
            assert len(evs) == 1 and evs[0]["action"] == "bucket_retune"
            # pre-compile-before-switch: the retune paid its compiles...
            assert engine.compile_count - c0 == evs[0]["compiles"] > 0
            # ...so steady-state traffic at the learned buckets is free
            c1 = engine.compile_count
            for b in learned:
                engine.infer(np.zeros((b, 4), np.float32))
            assert engine.compile_count == c1
        finally:
            batcher.shutdown(drain=False)


# ---------------------------------------------------------------------------
# SlotScaler: breach doubles, quiet halves, the estimator gates growth
# ---------------------------------------------------------------------------
class TestSlotScaler:
    def _scaler(self, **kw):
        calls = []

        def apply(n):
            calls.append(n)
            return {"slots": n, "previous": None, "changed": True}

        kw.setdefault("cooldown_s", 0.0)
        return SlotScaler(apply, slots=2, min_slots=1, max_slots=8,
                          idle_for_s=10.0, **kw), calls

    def test_breach_doubles_quiet_halves(self):
        scaler, calls = self._scaler()
        hub = _hub()
        breach = _Verdict("degraded", ["overload_rejections"])
        scaler.tick(0.0, breach, {"overload_rejections"}, hub)
        scaler.tick(1.0, breach, {"overload_rejections"}, hub)
        assert calls == [4, 8] and scaler.slots == 8
        # capped at max_slots
        scaler.tick(2.0, breach, {"overload_rejections"}, hub)
        assert calls == [4, 8]
        # quiet long enough → halve back down
        scaler.tick(13.0, _Verdict(), set(), hub)
        assert calls[-1] == 4 and scaler.slots == 4
        # idle window re-measures from the LAST breach
        scaler.tick(14.0, _Verdict(), set(), hub)  # cooldown_s=0, idle ok
        assert scaler.slots == 2

    def test_memory_estimator_gates_scale_up(self, monkeypatch):
        from deeplearning4j_tpu.serving import generate as gen_mod

        monkeypatch.setattr(
            gen_mod, "generation_memory_report",
            lambda model, n, max_length=None, draft_layers=0:
            {"total_bytes": 10 ** 12})
        scaler, calls = self._scaler(base_model=object(),
                                     memory_limit_bytes=1024)
        scaler.tick(0.0, _Verdict("degraded", ["overload_rejections"]),
                    {"overload_rejections"}, _hub())
        assert calls == [] and scaler.slots == 2

    def test_actions_feed_storm_counter(self):
        reg = MetricsRegistry()
        scaler, _ = self._scaler()
        hub = _hub(registry=reg)
        scaler.tick(0.0, _Verdict("degraded", ["overload_rejections"]),
                    {"overload_rejections"}, hub)
        assert reg.family_sum("controller_actions_total") == 1


# ---------------------------------------------------------------------------
# TenantDemoter against a real router; the gauge feeds the alert
# ---------------------------------------------------------------------------
class TestTenantDemoter:
    def test_demote_then_restore_after_quiet(self, tmp_path):
        from deeplearning4j_tpu.serving import ModelRegistry, ModelRouter
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish("m", save_checkpoint(_net(), str(tmp_path / "ck")),
                    score=0.5)
        router = ModelRouter(reg, refresh_s=60.0, max_wait_ms=1.0)
        try:
            demoter = TenantDemoter(router, restore_after_s=10.0,
                                    cooldown_s=0.0, abuse_share=0.5)
            hub = _hub(registry=router.metrics.registry)
            x = np.zeros((1, 4), np.float32)
            for _ in range(8):
                router.submit("m", x, tenant="spammy").result(timeout=10)
            router.submit("m", x, tenant="steady").result(timeout=10)
            breach = _Verdict("degraded", ["serving_latency_slo_breach"])
            demoter.tick(0.0, breach, {"serving_latency_slo_breach"},
                         hub)
            assert list(demoter.demoted) == ["spammy"]
            demoted_g = router.metrics.registry.get(
                "serving_tenants_demoted")
            assert demoted_g is not None and demoted_g.value() == 1
            # the gauge the demoter set IS the tenant_demoted alert
            # input — close the loop through the real rule pack
            ev = AlertEvaluator(default_rules(),
                                registry=router.metrics.registry,
                                min_tick_interval=0.0)
            ev.tick(0.0)
            ev.tick(1.0)
            assert "tenant_demoted" in ev.fired_names()
            # a demoted tenant hits its clamped quota with typed errors
            from deeplearning4j_tpu.serving import (
                TenantQuotaExceededError,
            )

            reqs = [router.submit("m", x, tenant="spammy")]
            with pytest.raises(TenantQuotaExceededError):
                for _ in range(8):
                    reqs.append(router.submit("m", x, tenant="spammy"))
            for r in reqs:
                try:
                    r.result(timeout=10)
                except Exception:  # noqa: BLE001 — drain; outcomes
                    # themselves are not under test here
                    pass
            # still breached → no restore; quiet long enough → restored
            demoter.tick(5.0, breach, {"serving_latency_slo_breach"},
                         hub)
            assert list(demoter.demoted) == ["spammy"]
            demoter.tick(16.0, _Verdict(), set(), hub)
            assert list(demoter.demoted) == []
            assert demoted_g.value() == 0
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# ModelPrewarmer: forecast-driven prewarm and idle eviction
# ---------------------------------------------------------------------------
class TestModelPrewarmer:
    def test_prewarm_then_evict_on_idle_forecast(self, tmp_path):
        from deeplearning4j_tpu.serving import ModelRegistry, ModelRouter
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish("hot", save_checkpoint(_net(1),
                                           str(tmp_path / "ck1")),
                    score=0.5)
        router = ModelRouter(reg, refresh_s=60.0, max_wait_ms=1.0)
        try:
            forecast = {"hot": 5.0}
            warmer = ModelPrewarmer(router, lambda t: forecast,
                                    warm_rps=1.0, evict_idle_s=0.0,
                                    cooldown_s=0.0)
            hub = _hub(registry=router.metrics.registry)
            seq = _flight.default_flight_recorder().recorded_total
            assert router.live_models() == []
            warmer.tick(0.0, _Verdict(), set(), hub)
            assert router.live_models() == ["hot"]
            # the first real request lands on an already-warm engine
            router.submit("hot", np.zeros((1, 4), np.float32)) \
                  .result(timeout=10)
            # forecast collapses → idle model evicted
            forecast.clear()
            time.sleep(0.05)
            warmer.tick(1.0, _Verdict(), set(), hub)
            assert router.live_models() == []
            kinds = [e["kind"] for e in _events_since(
                seq, {"controller_prewarm", "controller_evict"})]
            assert kinds == ["controller_prewarm", "controller_evict"]
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# ControllerHub: fault containment and the verdict fan-out
# ---------------------------------------------------------------------------
class TestControllerHub:
    def test_actuator_fault_contained(self):
        ticked = []

        class Boom:
            name = "boom"

            def tick(self, now, verdict, firing, hub):
                raise RuntimeError("actuator wedged")

        class Counts:
            name = "counts"

            def tick(self, now, verdict, firing, hub):
                ticked.append(now)

        hub = ControllerHub(AlertEvaluator([], min_tick_interval=0.0),
                            [Boom(), Counts()])
        verdict = hub.tick(0.0)
        assert hub.errors == 1
        assert ticked == [0.0]  # the loop survived the wedged actuator
        assert verdict.status in ("healthy", "unknown")
        assert any(r["action"] == "error" for r in hub.recent)

    def test_oscillation_drill_registered(self):
        from deeplearning4j_tpu.chaos import drills

        d = drills.DRILLS["controller_oscillation"]
        assert d.fast  # runs in test_chaos.py's fast matrix
        assert "serving_latency_slo_breach" in d.expected_alerts
        assert "controller.act" in d.seams


# ---------------------------------------------------------------------------
# the CLI surface (in-process; subprocess coverage in drive_loadgen.py)
# ---------------------------------------------------------------------------
def test_cli_loadgen_compile_only_json(capsys):
    from deeplearning4j_tpu import cli

    rc = cli.main(["loadgen", "--builtin", "cluster", "--compile-only",
                   "--json", "--duration-s", "5", "--seed", "2"])
    body = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert body["seed"] == 2 and body["n_requests"] > 0
    assert body["fingerprint"] == BUILTIN_PLANS["cluster"]().compile(
        duration_s=5.0, seed=2).fingerprint()
