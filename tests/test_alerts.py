"""SLO alert engine tests (obs/alerts.py, obs/slo.py): the hysteresis
state machine under a fake clock (pending hold, flap suppression,
resolve hysteresis — no sleeps anywhere), per-kind window math
(increase / rate / absence / multi-window burn rate), verdict
aggregation, flight-event signals, the canary-gate-as-rules parity,
content-negotiated /alerts on BOTH HTTP surfaces, incremental
/debug/flight polling, dump merging, and the generated alert-rule
table embed."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import deeplearning4j_tpu
from deeplearning4j_tpu.obs import events as obs_events
from deeplearning4j_tpu.obs import slo
from deeplearning4j_tpu.obs.alerts import (
    FLIGHT_EVENT_METRIC,
    AlertEvaluator,
    AlertRule,
    SLOObjective,
)
from deeplearning4j_tpu.obs.flight import (
    FlightRecorder,
    find_dumps,
    format_dump,
    merge_dumps,
)
from deeplearning4j_tpu.obs.metrics import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(deeplearning4j_tpu.__file__)))


class Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


def make_eval(rules, reg=None, recorder=None, **kw):
    clock = Clock()
    ev = AlertEvaluator(rules, registry=reg or MetricsRegistry(),
                        clock=clock, recorder=recorder,
                        min_tick_interval=0.0,
                        record_events=recorder is not None, **kw)
    return ev, clock, ev.registry


def states(ev):
    return {s["name"]: s for s in ev.states()}


# ==========================================================================
# the hysteresis state machine (fake clock, no sleeps)
# ==========================================================================
class TestStateMachine:
    def test_pending_hold_then_fire(self):
        rec = FlightRecorder()
        ev, clock, reg = make_eval(
            [AlertRule("t", "threshold", metric="g", op=">", threshold=5,
                       for_s=10, resolve_s=0)], recorder=rec)
        g = reg.gauge("g")
        ev.tick()
        assert states(ev)["t"]["state"] == "ok"
        g.set(9)
        clock.advance(1)
        ev.tick()
        assert states(ev)["t"]["state"] == "pending"
        clock.advance(5)  # 5s held < 10s
        ev.tick()
        assert states(ev)["t"]["state"] == "pending"
        clock.advance(6)  # 11s held
        ev.tick()
        st = states(ev)["t"]
        assert st["state"] == "firing" and st["fire_count"] == 1
        kinds = [e["kind"] for e in rec.events()]
        assert kinds == ["alert_pending", "alert_fired"]
        fired = rec.events()[-1]
        assert fired["alert"] == "t" and fired["severity"] == "warn"

    def test_flap_before_hold_never_fires(self):
        rec = FlightRecorder()
        ev, clock, reg = make_eval(
            [AlertRule("t", "threshold", metric="g", op=">", threshold=5,
                       for_s=10)], recorder=rec)
        g = reg.gauge("g")
        ev.tick()
        g.set(9)
        clock.advance(1)
        ev.tick()
        g.set(0)  # condition clears before the hold elapses
        clock.advance(5)
        ev.tick()
        assert states(ev)["t"]["state"] == "ok"
        g.set(9)
        clock.advance(1)
        ev.tick()
        # the hold RESTARTS: an earlier aborted pending must not count
        clock.advance(9)
        ev.tick()
        assert states(ev)["t"]["state"] == "pending"
        assert "alert_fired" not in [e["kind"] for e in rec.events()]

    def test_resolve_hysteresis_and_no_refire_on_dip(self):
        rec = FlightRecorder()
        ev, clock, reg = make_eval(
            [AlertRule("t", "threshold", metric="g", op=">", threshold=5,
                       for_s=0, resolve_s=20)], recorder=rec)
        g = reg.gauge("g")
        ev.tick()
        g.set(9)
        clock.advance(1)
        ev.tick()
        assert states(ev)["t"]["state"] == "firing"
        g.set(0)  # dip
        clock.advance(10)  # < resolve_s
        ev.tick()
        assert states(ev)["t"]["state"] == "firing"
        g.set(9)  # dip ended: still the SAME incident
        clock.advance(1)
        ev.tick()
        st = states(ev)["t"]
        assert st["state"] == "firing" and st["fire_count"] == 1
        g.set(0)
        clock.advance(1)
        ev.tick()
        clock.advance(21)  # clear >= resolve_s
        ev.tick()
        assert states(ev)["t"]["state"] == "ok"
        kinds = [e["kind"] for e in rec.events()]
        assert kinds == ["alert_pending", "alert_fired", "alert_resolved"]

    def test_firing_gauge_mirrors_state(self):
        ev, clock, reg = make_eval(
            [AlertRule("t", "threshold", metric="g", op=">", threshold=5)])
        reg.gauge("g").set(9)
        clock.advance(1)
        ev.tick()
        assert reg.get("alert_firing", {"alert": "t"}).value() == 1.0
        reg.gauge("g").set(0)
        clock.advance(1)
        ev.tick()
        assert reg.get("alert_firing", {"alert": "t"}).value() == 0.0

    def test_shutdown_zeroes_gauges(self):
        ev, clock, reg = make_eval(
            [AlertRule("t", "threshold", metric="g", op=">", threshold=5)])
        reg.gauge("g").set(9)
        clock.advance(1)
        ev.tick()
        ev.shutdown()
        assert reg.get("alert_firing", {"alert": "t"}).value() == 0.0

    def test_context_isolates_gauges_across_evaluators(self):
        """Two evaluators sharing one registry with the SAME rule
        names (concurrent canary windows for different models): the
        context labels are part of the gauge identity, so one
        window's shutdown cannot zero the other's live firing
        gauge."""
        reg = MetricsRegistry()
        clocks = [Clock(), Clock()]
        evs = []
        for i, model in enumerate(("a", "b")):
            ev = AlertEvaluator(
                [AlertRule("t", "threshold", metric=f"g{model}",
                           op=">", threshold=5)],
                registry=reg, clock=clocks[i],
                context={"model": model}, min_tick_interval=0.0,
                record_events=False)
            evs.append(ev)
        reg.gauge("ga").set(9)
        reg.gauge("gb").set(9)
        for ev, clock in zip(evs, clocks):
            clock.advance(1)
            ev.tick()
        la = {"alert": "t", "model": "a"}
        lb = {"alert": "t", "model": "b"}
        assert reg.get("alert_firing", la).value() == 1.0
        assert reg.get("alert_firing", lb).value() == 1.0
        evs[1].shutdown()  # model b's window ends
        assert reg.get("alert_firing", lb).value() == 0.0
        assert reg.get("alert_firing", la).value() == 1.0  # a untouched


# ==========================================================================
# rule kinds: window math
# ==========================================================================
class TestRuleKinds:
    def test_increase_measured_against_window_edge(self):
        ev, clock, reg = make_eval(
            [AlertRule("i", "increase", family="c_total",
                       op=">=", threshold=3, window_s=100)])
        c = reg.counter("c_total")
        ev.tick()          # t=0: baseline sample 0
        c.inc(2)
        clock.advance(60)
        ev.tick()          # delta 2 over 60s: below the 3 floor
        assert states(ev)["i"]["state"] == "ok"
        c.inc(1)
        clock.advance(110)  # t=170: edge at 70 -> baseline is t=60 (2)
        ev.tick()
        # growth older than the window has aged out: delta is 1, not 3
        st = states(ev)["i"]
        assert st["state"] == "ok" and st["value"] == 1.0
        c.inc(3)
        clock.advance(5)   # t=175: baseline still t=60 -> delta 4
        ev.tick()
        st = states(ev)["i"]
        assert st["state"] == "firing" and st["value"] == 4.0

    def test_rate_math_exact(self):
        ev, clock, reg = make_eval(
            [AlertRule("r", "rate", family="c_total", op=">",
                       threshold=0.5, window_s=1000)])
        c = reg.counter("c_total")
        ev.tick()
        c.inc(30)
        clock.advance(100)
        ev.tick()
        st = states(ev)["r"]
        assert st["state"] == "ok" and st["value"] == pytest.approx(0.3)
        c.inc(60)
        clock.advance(100)
        ev.tick()
        st = states(ev)["r"]
        # 90 over 200s = 0.45 vs baseline at t=0 — still under
        assert st["state"] == "ok" and st["value"] == pytest.approx(0.45)
        c.inc(100)
        clock.advance(100)
        ev.tick()
        assert states(ev)["r"]["state"] == "firing"

    def test_absence_requires_activity_then_fires_and_resolves(self):
        ev, clock, reg = make_eval(
            [AlertRule("a", "absence", family="c_total", stale_s=100)])
        c = reg.counter("c_total")
        ev.tick()
        clock.advance(500)  # silent forever but NEVER active: no page
        ev.tick()
        assert states(ev)["a"]["state"] == "ok"
        c.inc()
        clock.advance(10)
        ev.tick()  # activity seen
        clock.advance(101)
        ev.tick()
        assert states(ev)["a"]["state"] == "firing"
        c.inc()  # the signal moved again
        clock.advance(1)
        ev.tick()
        assert states(ev)["a"]["state"] == "ok"

    def test_absence_without_activity_requirement(self):
        ev, clock, reg = make_eval(
            [AlertRule("a", "absence", family="c_total", stale_s=100,
                       require_activity=False)])
        ev.tick()
        clock.advance(101)
        ev.tick()
        assert states(ev)["a"]["state"] == "firing"

    def test_burn_rate_requires_every_window(self):
        obj = SLOObjective("slo", bad="bad_total", total="all_total",
                          target=0.99)
        ev, clock, reg = make_eval(
            [AlertRule("b", "burn_rate", objective=obj,
                       windows=[(600, 2.0), (60, 2.0)])])
        bad, tot = reg.counter("bad_total"), reg.counter("all_total")
        ev.tick()
        # a live burst at realistic scrape cadence fires both legs
        # (ratio 0.1 >= 2x the 0.01 budget)
        bad.inc(10)
        tot.inc(100)
        clock.advance(30)
        ev.tick()
        assert states(ev)["b"]["state"] == "firing"
        # ... but once the burn STOPS, the short window sees only the
        # recent clean traffic and the page clears — even though the
        # long window still contains the burst
        for _ in range(10):
            tot.inc(100)
            clock.advance(30)
            ev.tick()
        assert states(ev)["b"]["state"] == "ok"

    def test_burn_rate_scrape_gap_cannot_page_for_a_dead_burst(self):
        """Scrape-driven evaluation with a gap wider than the short
        window: the only baseline old enough is ANCIENT, and measuring
        across the gap would page at t=600 for a burst that ended at
        t=30 — insufficient history must mean no verdict instead."""
        obj = SLOObjective("slo", bad="bad_total", total="all_total",
                          target=0.99)
        ev, clock, reg = make_eval(
            [AlertRule("b", "burn_rate", objective=obj,
                       windows=[(600, 2.0), (60, 2.0)])])
        bad, tot = reg.counter("bad_total"), reg.counter("all_total")
        ev.tick()
        bad.inc(10)
        tot.inc(100)  # the burst happens... and nobody scrapes
        clock.advance(600)
        ev.tick()
        assert states(ev)["b"]["state"] == "ok"

    def test_burn_rate_boundary_is_inclusive_and_needs_traffic(self):
        obj = SLOObjective("slo", bad="bad_total", total="all_total",
                          target=0.9)  # budget 0.1
        ev, clock, reg = make_eval(
            [AlertRule("b", "burn_rate", objective=obj,
                       windows=[(100, 2.0)])])
        bad, tot = reg.counter("bad_total"), reg.counter("all_total")
        ev.tick()
        clock.advance(10)
        ev.tick()  # no traffic at all: no verdict
        assert states(ev)["b"]["state"] == "ok"
        bad.inc(20)
        tot.inc(100)  # ratio 0.2 == 2.0 * 0.1 exactly: >= fires
        clock.advance(10)
        ev.tick()
        assert states(ev)["b"]["state"] == "firing"

    def test_fn_signal_none_and_reason(self):
        out = {"v": None}
        ev, clock, _ = make_eval(
            [AlertRule("f", "threshold", fn=lambda: out["v"],
                       op=">", threshold=0.5)])
        ev.tick()
        assert states(ev)["f"]["state"] == "ok"
        out["v"] = (1.0, "custom reason text")
        clock.advance(1)
        ev.tick()
        st = states(ev)["f"]
        assert st["state"] == "firing" and st["reason"] == \
            "custom reason text"

    def test_missing_metric_is_zero_for_counter_kinds_only(self):
        ev, clock, reg = make_eval([
            AlertRule("t", "threshold", metric="nope", op="<",
                      threshold=5),
            AlertRule("i", "increase", metric="later_total",
                      window_s=500),
        ])
        ev.tick()
        # threshold on missing data is NO verdict, not "value 0 < 5"
        assert states(ev)["t"]["state"] == "ok"
        # the counter materializes after baseline: its first increments
        # must still register as an increase from 0
        reg.counter("later_total").inc(4)
        clock.advance(10)
        ev.tick()
        assert states(ev)["i"]["state"] == "firing"


# ==========================================================================
# construction validation + verdict + evaluator plumbing
# ==========================================================================
class TestEvaluator:
    def test_typed_construction_errors(self):
        with pytest.raises(ValueError):
            AlertRule("x", "nope", metric="m")
        with pytest.raises(ValueError):
            AlertRule("x", "threshold", metric="m", severity="page")
        with pytest.raises(ValueError):
            AlertRule("x", "threshold", metric="m", op="!=")
        with pytest.raises(ValueError):
            AlertRule("x", "threshold")  # no signal
        with pytest.raises(ValueError):
            AlertRule("x", "threshold", metric="m", family="f")
        with pytest.raises(ValueError):
            AlertRule("x", "burn_rate")  # no objective/windows
        with pytest.raises(ValueError):
            AlertRule("x", "absence", metric="m")  # no stale_s
        with pytest.raises(ValueError):
            AlertEvaluator([AlertRule("d", "threshold", metric="m"),
                            AlertRule("d", "threshold", metric="m")],
                           registry=MetricsRegistry())

    def test_verdict_aggregation(self):
        ev, clock, reg = make_eval([
            AlertRule("w", "threshold", metric="g1", op=">", threshold=1,
                      severity="warn"),
            AlertRule("c", "threshold", metric="g2", op=">", threshold=1,
                      severity="critical"),
        ])
        assert ev.verdict().status == "unknown"
        ev.tick()
        assert ev.verdict().status == "healthy"
        assert ev.verdict().healthy
        reg.gauge("g1").set(5)
        clock.advance(1)
        ev.tick()
        assert ev.verdict().status == "degraded"
        reg.gauge("g2").set(5)
        clock.advance(1)
        ev.tick()
        v = ev.verdict()
        assert v.status == "critical" and len(v.firing) == 2
        assert not v.healthy

    def test_watch_flight_counts_and_unwatch_stops(self):
        rec = FlightRecorder()
        ev, clock, reg = make_eval(
            [AlertRule("n", "increase", window_s=500,
                       metric=FLIGHT_EVENT_METRIC,
                       labels={"kind": "nan_skip"})])
        ev.watch_flight(rec)
        ev.tick()
        rec.record("nan_skip", consec=1)
        rec.record("step", iteration=1)
        clock.advance(10)
        ev.tick()
        assert states(ev)["n"]["state"] == "firing"
        assert reg.get(FLIGHT_EVENT_METRIC,
                       {"kind": "step"}).value() == 1.0
        ev.unwatch()
        rec.record("nan_skip", consec=2)
        assert reg.get(FLIGHT_EVENT_METRIC,
                       {"kind": "nan_skip"}).value() == 1.0

    def test_maybe_tick_throttles(self):
        ev = AlertEvaluator([AlertRule("t", "threshold", metric="g")],
                            registry=MetricsRegistry(),
                            min_tick_interval=3600.0,
                            record_events=False)
        assert ev.maybe_tick() is True
        assert ev.maybe_tick() is False  # within the interval
        assert ev.ticks == 1

    def test_prometheus_text_lists_non_ok_only(self):
        ev, clock, reg = make_eval([
            AlertRule("fire", "threshold", metric="g", op=">",
                      threshold=1, severity="critical"),
            AlertRule("hold", "threshold", metric="g", op=">",
                      threshold=1, for_s=100),
            AlertRule("quiet", "threshold", metric="g", op="<",
                      threshold=-1),
        ])
        reg.gauge("g").set(5)
        clock.advance(1)
        ev.tick()
        txt = ev.prometheus_text()
        assert ('ALERTS{alertname="fire",alertstate="firing",'
                'severity="critical"} 1') in txt
        assert 'alertname="hold",alertstate="pending"' in txt
        assert "quiet" not in txt

    def test_context_rides_on_events(self):
        rec = FlightRecorder()
        ev = AlertEvaluator(
            [AlertRule("t", "threshold", metric="g", op=">",
                       threshold=1)],
            registry=MetricsRegistry(), clock=Clock(), recorder=rec,
            context={"model": "m", "version": 2},
            min_tick_interval=0.0)
        ev.registry.gauge("g").set(5)
        ev.tick()
        fired = [e for e in rec.events() if e["kind"] == "alert_fired"]
        assert fired and fired[0]["model"] == "m" \
            and fired[0]["version"] == 2


# ==========================================================================
# the rule pack + the canary gate as rules
# ==========================================================================
class TestRulePack:
    def test_pack_names_exactly_match_declared_alerts(self):
        assert set(slo.pack_rule_names()) == set(obs_events.ALERTS)

    def test_alert_events_declared(self):
        for k in ("alert_pending", "alert_fired", "alert_resolved"):
            assert obs_events.is_declared_event(k)

    def test_default_pack_constructs_and_evaluates_clean(self):
        ev, clock, _reg = make_eval(slo.default_rules())
        ev.tick()
        clock.advance(60)
        ev.tick()
        assert ev.verdict().status == "healthy"

    def _mm(self):
        class Stats:
            def __init__(self):
                self.requests = 0
                self.score = None
                self.latency_sum = 0.0
                self.gen_requests = 0
                self.gen_latency_sum = 0.0

            def mean_latency(self):
                return (self.latency_sum / self.requests
                        if self.requests else None)

            def mean_gen_latency(self):
                return (self.gen_latency_sum / self.gen_requests
                        if self.gen_requests else None)

        class VE:
            def __init__(self):
                self.stats = Stats()

        class MM:
            active = None
            canary = None

        mm = MM()
        mm.active, mm.canary = VE(), VE()
        return mm

    def test_canary_gate_rules_reproduce_pr11_decisions(self):
        mm = self._mm()
        rules = slo.canary_gate_rules(
            mm, higher_is_better=False, latency_trip_mult=5.0,
            latency_trip_min_samples=8, score_trip_tolerance=0.0)
        assert [r.name for r in rules] == [
            "canary_score_regressed", "canary_latency_regressed",
            "canary_generation_latency_regressed"]
        ev = AlertEvaluator(rules, registry=MetricsRegistry(),
                            clock=Clock(), min_tick_interval=0.0,
                            record_events=False)
        ev.tick()
        assert ev.firing() == []  # no scores, no samples: no verdict
        # score regression (lower is better): canary worse -> fires
        # with the ORIGINAL reason string
        mm.active.stats.score = 0.5
        mm.canary.stats.score = 0.6
        ev.tick()
        firing = ev.firing()
        assert [f["name"] for f in firing] == ["canary_score_regressed"]
        assert firing[0]["reason"] == \
            "score regressed: canary 0.6 vs active 0.5"
        # latency gate honors the min-sample floor exactly
        mm.canary.stats.score = 0.5  # clear the score leg
        mm.canary.stats.requests = 7
        mm.canary.stats.latency_sum = 7 * 10.0
        mm.active.stats.requests = 8
        mm.active.stats.latency_sum = 8 * 0.001
        ev.tick()
        assert "canary_latency_regressed" not in \
            [f["name"] for f in ev.firing()]
        mm.canary.stats.requests = 8
        mm.canary.stats.latency_sum = 8 * 10.0
        ev.tick()
        names = [f["name"] for f in ev.firing()]
        assert "canary_latency_regressed" in names
        reason = [f for f in ev.firing()
                  if f["name"] == "canary_latency_regressed"][0]["reason"]
        assert reason == ("latency regressed: canary 10000.0ms vs "
                          "active 1.0ms (x5 gate)")

    def test_canary_gen_latency_compares_only_generation(self):
        mm = self._mm()
        rules = slo.canary_gate_rules(
            mm, higher_is_better=False, latency_trip_mult=5.0,
            latency_trip_min_samples=2, score_trip_tolerance=0.0)
        ev = AlertEvaluator(rules, registry=MetricsRegistry(),
                            clock=Clock(), min_tick_interval=0.0,
                            record_events=False)
        mm.canary.stats.gen_requests = 2
        mm.canary.stats.gen_latency_sum = 2 * 10.0
        mm.active.stats.gen_requests = 2
        mm.active.stats.gen_latency_sum = 2 * 0.1
        ev.tick()
        assert [f["name"] for f in ev.firing()] == \
            ["canary_generation_latency_regressed"]


# ==========================================================================
# doc table embed (the flight-event-table contract, for alerts)
# ==========================================================================
def test_alert_table_matches_architecture_doc():
    from deeplearning4j_tpu.analysis.tables import render_alert_table

    arch = open(os.path.join(REPO_ROOT, "ARCHITECTURE.md")).read()
    assert render_alert_table() in arch


# ==========================================================================
# flight ring: incremental polling + dump merging
# ==========================================================================
class TestFlightIncrementalAndMerge:
    def test_snapshot_since_seq(self):
        rec = FlightRecorder()
        rec.record("step", iteration=1)
        rec.record("step", iteration=2)
        s1 = rec.snapshot()
        assert s1["next_since_seq"] == 1
        rec.record("nan_skip", consec=1)
        s2 = rec.snapshot(since_seq=s1["next_since_seq"])
        assert [e["kind"] for e in s2["events"]] == ["nan_skip"]
        assert s2["next_since_seq"] == 2
        # idempotent cursor: nothing new echoes the cursor back
        s3 = rec.snapshot(since_seq=s2["next_since_seq"])
        assert s3["events"] == [] and s3["next_since_seq"] == 2

    def test_merge_dumps_time_orders_across_pids(self, tmp_path):
        r1, r2 = FlightRecorder(), FlightRecorder()
        r1.record("step", iteration=1)
        r2.record("publish", model="m")
        r1.record("fit_end", iteration=2)
        b1, b2 = r1.snapshot(), r2.snapshot()
        b1["pid"], b2["pid"] = 111, 222
        merged = merge_dumps([b1, b2])
        assert merged["merged"] and len(merged["events"]) == 3
        ts = [e["ts"] for e in merged["events"]]
        assert ts == sorted(ts)
        assert {e["pid"] for e in merged["events"]} == {111, 222}
        text = format_dump(merged)
        assert "merged timeline" in text and "publish" in text

    def test_find_dumps_and_cli_merge(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import flight_dump_main

        r1, r2 = FlightRecorder(), FlightRecorder()
        r1.record("step", iteration=1)
        r2.record("publish", model="m")
        p1 = str(tmp_path / "flight_recorder_1111.json")
        p2 = str(tmp_path / "flight_recorder_2222.json")
        assert r1.dump(path=p1) and r2.dump(path=p2)
        assert find_dumps(str(tmp_path)) == [p1, p2]
        assert flight_dump_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "merged timeline" in out and "publish" in out \
            and "step" in out
        # single file keeps the classic single-ring rendering
        assert flight_dump_main([p1]) == 0
        out = capsys.readouterr().out
        assert "merged timeline" not in out
        # --json merged body round-trips
        assert flight_dump_main([p1, p2, "--json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert body["merged"] and len(body["events"]) == 2

    def test_cli_missing_path_fails(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import flight_dump_main

        assert flight_dump_main([str(tmp_path / "nope")]) == 1


# ==========================================================================
# HTTP surfaces (content negotiation on both servers)
# ==========================================================================
def _get(url, accept=None):
    req = urllib.request.Request(
        url, headers={} if accept is None else {"Accept": accept})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return (resp.status, resp.headers.get("Content-Type"),
                    resp.read())
    except urllib.error.HTTPError as e:  # 4xx still carries the body
        return e.code, e.headers.get("Content-Type"), e.read()


class TestHTTPSurfaces:
    def _evaluator(self, reg):
        rec = FlightRecorder()
        ev = AlertEvaluator(slo.default_rules(), registry=reg,
                            recorder=rec, min_tick_interval=0.0)
        ev.watch_flight(rec)
        return ev, rec

    def test_metrics_server_alerts_negotiated_and_verdict(self):
        from deeplearning4j_tpu.obs.exporter import MetricsServer

        reg = MetricsRegistry()
        ev, rec = self._evaluator(reg)
        srv = MetricsServer(registry=reg, port=0, alerts=ev)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            _s, _c, body = _get(base + "/alerts")
            body = json.loads(body)
            assert body["verdict"]["status"] == "healthy"
            rec.record("storage_error", op="fsync", surface="checkpoint")
            _s, _c, body = _get(base + "/alerts")
            firing = [a["name"] for a in json.loads(body)["alerts"]
                      if a["state"] == "firing"]
            assert "storage_errors" in firing
            _s, ctype, text = _get(base + "/alerts",
                                   accept="text/plain")
            assert ctype.startswith("text/plain")
            assert b'alertname="storage_errors"' in text
            _s, _c, h = _get(base + "/healthz")
            assert json.loads(h)["verdict"]["status"] == "critical"
        finally:
            srv.shutdown()

    def test_serving_server_alerts_and_flight_polling(self):
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import (
            DenseLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.serving.engine import InferenceEngine
        from deeplearning4j_tpu.serving.server import InferenceServer

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        model = MultiLayerNetwork(conf).init()
        srv = InferenceServer(InferenceEngine(model), port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            _s, _c, body = _get(base + "/alerts")
            body = json.loads(body)
            assert {a["name"] for a in body["alerts"]} == \
                set(slo.pack_rule_names()) - {
                    "canary_score_regressed", "canary_latency_regressed",
                    "canary_generation_latency_regressed"}
            _s, _c, h = _get(base + "/healthz")
            assert "verdict" in json.loads(h)
            _s, _c, f1 = _get(base + "/debug/flight")
            cur = json.loads(f1)["next_since_seq"]
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("step", iteration=123)
            _s, _c, f2 = _get(base + f"/debug/flight?since_seq={cur}")
            evs = json.loads(f2)["events"]
            assert any(e["kind"] == "step" and e.get("iteration") == 123
                       for e in evs)
            assert all(e["seq"] > cur for e in evs)
            _s, _c, bad = _get(base + "/debug/flight?since_seq=zzz")
            # malformed cursor is the client's error, mapped typed
            assert json.loads(bad).get("error") == "ValueError"
        finally:
            srv.shutdown()


# ==========================================================================
# cli alerts (one-shot rendering + exit codes)
# ==========================================================================
class TestCliAlerts:
    def test_one_shot_renders_and_exit_code(self, capsys):
        from deeplearning4j_tpu.cli import alerts_main
        from deeplearning4j_tpu.obs.exporter import MetricsServer

        reg = MetricsRegistry()
        rec = FlightRecorder()
        ev = AlertEvaluator(slo.default_rules(), registry=reg,
                            recorder=rec, min_tick_interval=0.0)
        ev.watch_flight(rec)
        srv = MetricsServer(registry=reg, port=0, alerts=ev).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            assert alerts_main([base]) == 0
            out = capsys.readouterr().out
            assert "verdict: HEALTHY" in out
            rec.record("lock_cycle", cycle="a->b->a")
            assert alerts_main([base, "--firing-only"]) == 2  # critical
            out = capsys.readouterr().out
            assert "lock_cycle_detected" in out \
                and "nan_step_storm" not in out
        finally:
            srv.shutdown()

    def test_unreachable_url_fails_typed(self, capsys):
        from deeplearning4j_tpu.cli import alerts_main

        assert alerts_main(["http://127.0.0.1:1/alerts"]) == 1
        assert "cannot reach" in capsys.readouterr().err
