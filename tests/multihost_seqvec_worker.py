"""Worker for the distributed-embedding parity test (VERDICT r3 item 6;
capability match for the reference's dl4j-spark-nlp
``Word2VecPerformer.java``): each process builds the SAME vocabulary from
the full corpus (TextPipeline role), trains skip-gram on its sentence
shard, parameter-averages at epoch boundaries, and dumps the final
embedding matrix for the parent to compare against single-process
training.

Usage: python multihost_seqvec_worker.py <coordinator> <nprocs> <pid> <outdir>
"""

import os
import sys

coordinator, nprocs, pid, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel.multihost import initialize  # noqa: E402
from deeplearning4j_tpu.nlp.distributed import (  # noqa: E402
    DistributedSequenceVectors,
)
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors  # noqa: E402
from tests.seqvec_corpus import build_corpus_and_vocab  # noqa: E402

ctx = initialize(coordinator, num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs

vocab, seqs = build_corpus_and_vocab()
sv = SequenceVectors(vocab, layer_size=24, window=3, negative=5,
                     learning_rate=0.05, epochs=8, batch_size=256, seed=7)
dist = DistributedSequenceVectors(sv)
dist.fit_sequences(seqs)

assert dist.sync_count >= 8, dist.sync_count

# the Word2Vec facade routes through the distributed trainer by itself
# when process_count > 1 (word2vec.py fit) — user-surface proof
from deeplearning4j_tpu.nlp.word2vec import Word2Vec  # noqa: E402

sentences = ["the quick brown fox jumps over the lazy dog",
             "the lazy dog sleeps while the quick fox runs",
             "brown fox and lazy dog play in the sun"] * 10
w2v = (Word2Vec.builder().iterate(sentences).layer_size(12).window_size(2)
       .min_word_frequency(1).epochs(2).seed(3).build().fit())
w2v_m = w2v.get_word_vector_matrix()

if pid == 0:
    np.savez(os.path.join(outdir, "seqvec_dist.npz"),
             syn0=sv.get_word_vector_matrix(),
             sync_count=dist.sync_count, w2v=w2v_m)
else:
    np.savez(os.path.join(outdir, f"seqvec_dist_{pid}.npz"),
             syn0=sv.get_word_vector_matrix(), w2v=w2v_m)
print(f"seqvec worker {pid}: done, syncs={dist.sync_count}", flush=True)
