"""Pipelined training loop (train/pipeline.py): in-graph multi-step
bundling via lax.scan, device prefetch, sync-free listener path.

The backbone assertions are BIT-exactness: a fit at ``steps_per_call=K``
must leave params AND updater slots (Adam m/v incl. the bias-correction
clock) exactly equal to the same fit at K=1 — including a NaN batch
inside a bundle under a FaultPolicy, the ragged epoch tail, and every
data-parallel runtime (ParallelWrapper std + ZeRO-1, SharedTrainingMaster,
DistributedLMTrainer).
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    BatchBundle,
    DeviceDataSet,
    ExistingDataSetIterator,
    ListDataSetIterator,
    iter_bundled,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, LSTM, OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import faults, pipeline
from deeplearning4j_tpu.train.listeners import (
    CollectScoresIterationListener,
    ScoreIterationListener,
    TrainingListener,
)
from deeplearning4j_tpu.updaters import Adam


def _batches(n, b=8, d=12, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return [
        DataSet(rng.standard_normal((b, d)).astype(np.float32),
                np.eye(c, dtype=np.float32)[rng.integers(0, c, b)])
        for _ in range(n)
    ]


def _mlp(k=1, fault_policy=None, seed=7):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .steps_per_call(k))
    if fault_policy is not None:
        b = b.fault_policy(fault_policy)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def _assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestBundledParity:
    def test_k4_bit_exact_incl_ragged_tail(self):
        """10 batches at K=4 → two bundles + two ragged singles per
        epoch; params, Adam slots and per-step scores must match K=1
        exactly over 2 epochs."""
        data = _batches(10)
        a, b = _mlp(1), _mlp(4)
        ca, cb = (CollectScoresIterationListener(frequency=1),
                  CollectScoresIterationListener(frequency=1))
        a.set_listeners(ca)
        b.set_listeners(cb)
        a.fit(ExistingDataSetIterator(data), epochs=1)
        b.fit(ExistingDataSetIterator(data), epochs=1)
        assert a.iteration == b.iteration == 10
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)
        assert [i for i, _ in ca.scores] == [i for i, _ in cb.scores]
        np.testing.assert_array_equal(
            np.asarray([s for _, s in ca.scores], np.float32),
            np.asarray([s for _, s in cb.scores], np.float32))

    def test_nan_batch_inside_bundle_matches_unbundled_skip(self):
        """A NaN gradient at step 2 — mid-bundle at K=4 — must skip the
        update exactly as the unbundled guarded loop does: params AND
        Adam slots bit-equal, bad/good counters equal."""
        data = _batches(4)
        with faults.fault_injection(nan_grad_steps=[2]):
            a = _mlp(1, fault_policy=True)
            a.fit(ExistingDataSetIterator(data), epochs=1)
        with faults.fault_injection(nan_grad_steps=[2]):
            b = _mlp(4, fault_policy=True)
            b.fit(ExistingDataSetIterator(data), epochs=1)
        assert a.bad_step_count == b.bad_step_count == 1
        assert (int(a.fault_state_["good_count"])
                == int(b.fault_state_["good_count"]) == 3)
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)

    def test_divergence_tripwire_trips_at_bundle_end(self):
        """The tripwire is checked once per bundle on the final consec: a
        bad streak filling the tail of a bundle still raises."""
        data = _batches(8)
        policy = faults.FaultPolicy(skip_nonfinite=True,
                                    max_consecutive_bad_steps=2)
        with faults.fault_injection(nan_grad_steps=[2, 3]):
            net = _mlp(4, fault_policy=policy)
            with pytest.raises(faults.TrainingDivergedError):
                net.fit(ExistingDataSetIterator(data), epochs=1)

    def test_computation_graph_bundled_parity(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((40, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 40)]

        def build(k):
            from deeplearning4j_tpu.nn.graph import ComputationGraph

            conf = (NeuralNetConfiguration.builder().seed(5)
                    .updater(Adam(1e-3)).steps_per_call(k)
                    .graph_builder()
                    .add_inputs("in")
                    .add_layer("d0", DenseLayer(n_out=8, activation="tanh"),
                               "in")
                    .add_layer("out", OutputLayer(n_out=3,
                                                  activation="softmax",
                                                  loss="mcxent"), "d0")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4))
                    .build())
            return ComputationGraph(conf).init()

        a, b = build(1), build(2)
        a.fit(DataSet(x, y), epochs=2, batch_size=8)
        b.fit(DataSet(x, y), epochs=2, batch_size=8)
        assert a.iteration == b.iteration == 10
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)


class TestBundlingLegality:
    def test_tbptt_rejects_bundling(self):
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
                .steps_per_call(4).list()
                .layer(LSTM(n_out=6))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .backprop_type("tbptt", fwd_length=4, back_length=4)
                .set_input_type(InputType.recurrent(3, 8))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        f = rng.standard_normal((4, 8, 3)).astype(np.float32)
        l = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 8))]
        with pytest.raises(ValueError, match="tBPTT"):
            net.fit(DataSet(f, l))

    def test_per_step_host_hooks_force_k1(self):
        class BackwardHook(TrainingListener):
            def __init__(self):
                self.calls = 0

            def on_backward_pass(self, model):
                self.calls += 1

        data = _batches(4)
        net = _mlp(4)
        hook = BackwardHook()
        net.set_listeners(hook)
        assert pipeline.bundling_blockers([hook]) == [
            "BackwardHook.on_backward_pass"]
        assert pipeline.resolve_steps_per_call(net) == 1
        net.fit(ExistingDataSetIterator(data), epochs=1)
        assert hook.calls == 4  # every step ran unbundled

    def test_state_coupled_listeners_force_k1(self, tmp_path):
        """Iteration-triggered CheckpointListener (and ProfilerListener)
        snapshot the MODEL per iteration — post-bundle replay would hand
        them end-of-bundle state, so they force K=1; epoch-triggered
        checkpoints bundle fine."""
        from deeplearning4j_tpu.train.listeners import (
            CheckpointListener,
            ProfilerListener,
        )

        per_iter = CheckpointListener(str(tmp_path),
                                      save_every_n_iterations=1)
        per_epoch = CheckpointListener(str(tmp_path),
                                       save_every_n_epochs=1)
        prof = ProfilerListener(str(tmp_path))
        assert pipeline.bundling_blockers([per_iter]) == [
            "CheckpointListener.requires_per_step_state"]
        assert pipeline.bundling_blockers([prof]) == [
            "ProfilerListener.requires_per_step_state"]
        assert pipeline.bundling_blockers([per_epoch]) == []
        net = _mlp(4)
        net.set_listeners(per_iter)
        assert pipeline.resolve_steps_per_call(net) == 1
        net.set_listeners(per_epoch)
        assert pipeline.resolve_steps_per_call(net) == 4

    def test_stats_listener_bundles(self):
        """StatsListener (default config) no longer forces K=1: the
        per-step signals it used to snapshot from live params now arrive
        through the in-graph telemetry stream (obs/telemetry.py), and
        param summaries are taken at bundle granularity. Only the opt-in
        introspection collections still block bundling — they genuinely
        need per-step gradient/activation tensors."""
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

        stats = StatsListener(InMemoryStatsStorage(), session_id="audit")
        assert pipeline.bundling_blockers([stats]) == []
        net = _mlp(4)
        net.set_listeners(stats)
        assert pipeline.resolve_steps_per_call(net) == 4
        grads = StatsListener(InMemoryStatsStorage(), session_id="audit2",
                              collect_gradients=True)
        assert pipeline.bundling_blockers([grads]) == [
            "StatsListener.on_gradient_calculation"]
        net.set_listeners(grads)
        assert pipeline.resolve_steps_per_call(net) == 1
        net.set_listeners()
        assert pipeline.resolve_steps_per_call(net) == 4

    def test_evaluative_listener_iteration_end_forces_k1(self):
        from deeplearning4j_tpu.train.listeners import EvaluativeListener

        per_iter = EvaluativeListener(None, invocation="iteration_end")
        per_epoch = EvaluativeListener(None, invocation="epoch_end")
        assert pipeline.bundling_blockers([per_iter]) == [
            "EvaluativeListener.requires_per_step_state"]
        assert pipeline.bundling_blockers([per_epoch]) == []

    def test_composable_listener_reports_children_not_itself(self):
        """ComposableIterationListener's delegating hook overrides must
        not read as always-blocking: it reports its CHILDREN's needs."""
        from deeplearning4j_tpu.train.listeners import (
            ComposableIterationListener,
        )

        plain = ComposableIterationListener(
            ScoreIterationListener(printer=lambda s: None))
        assert pipeline.bundling_blockers([plain]) == []

        class BackwardHook(TrainingListener):
            def on_backward_pass(self, model):
                pass

        nested = ComposableIterationListener(BackwardHook())
        assert pipeline.bundling_blockers([nested]) == [
            "BackwardHook.on_backward_pass"]

    def test_composable_children_keep_sync_free_path(self, monkeypatch):
        """A composed CollectScores listener keeps the once-per-bundle
        fetch (the composite delegates bundle_done, it doesn't fall to
        the per-step model.score() replay)."""
        from deeplearning4j_tpu.train.listeners import (
            ComposableIterationListener,
        )

        data = _batches(8)
        net = _mlp(4)
        cs = CollectScoresIterationListener(frequency=1)
        net.set_listeners(ComposableIterationListener(cs))

        def banned_score(ds=None):
            raise AssertionError("model.score() sync inside a bundled fit")

        monkeypatch.setattr(net, "score", banned_score)
        before = pipeline._host_fetches
        net.fit(ExistingDataSetIterator(data), epochs=1)
        assert pipeline._host_fetches - before == 2  # one per bundle
        assert [i for i, _ in cs.scores] == list(range(1, 9))

    def test_shape_change_flushes_to_singles(self):
        small = _batches(3, b=8)
        big = _batches(3, b=16, seed=1)
        items = list(iter_bundled(iter(small + big), 2))
        kinds = [type(i).__name__ for i in items]
        # 1 bundle of 8s, ragged 8 flushed as single, 1 bundle of 16s,
        # ragged 16 single
        assert kinds == ["BatchBundle", "DataSet", "BatchBundle", "DataSet"]
        assert items[0].features.shape == (2, 8, 12)
        assert items[2].features.shape == (2, 16, 12)


class TestSyncFreeListeners:
    def test_bundle_scores_fetched_once_no_model_score_sync(self,
                                                            monkeypatch):
        """Inside a bundled fit, Score/CollectScores listeners must never
        call model.score() (a per-step host sync) and must fetch the
        stacked device losses at most once per bundle."""
        data = _batches(8)
        baseline = _mlp(1)
        cb0 = CollectScoresIterationListener(frequency=1)
        baseline.set_listeners(cb0)
        baseline.fit(ExistingDataSetIterator(data), epochs=1)

        net = _mlp(4)
        printed = []
        cs = CollectScoresIterationListener(frequency=1)
        si = ScoreIterationListener(print_iterations=2,
                                    printer=printed.append)
        net.set_listeners(cs, si)

        def banned_score(ds=None):
            raise AssertionError(
                "model.score() host sync inside a bundled fit")

        monkeypatch.setattr(net, "score", banned_score)
        fetches_before = pipeline._host_fetches
        net.fit(ExistingDataSetIterator(data), epochs=1)
        # 8 batches at K=4 = 2 bundles; one shared host fetch per bundle
        assert pipeline._host_fetches - fetches_before == 2
        assert len(printed) == 4  # iterations 2, 4, 6, 8
        np.testing.assert_array_equal(
            np.asarray([s for _, s in cs.scores], np.float32),
            np.asarray([s for _, s in cb0.scores], np.float32))

    def test_no_fetch_when_no_reporting_hit(self):
        """A bundle containing no reporting iteration must not fetch at
        all (ScoreIterationListener at a sparse frequency)."""
        data = _batches(4)
        net = _mlp(4)
        net.set_listeners(ScoreIterationListener(print_iterations=100,
                                                 printer=lambda s: None))
        before = pipeline._host_fetches
        net.fit(ExistingDataSetIterator(data), epochs=1)
        assert pipeline._host_fetches == before

    def test_legacy_listener_gets_per_step_device_score(self):
        """Listeners without bundle_done keep their per-step
        iteration_done contract, with model.score_ rebound to the step's
        device scalar."""
        seen = []

        class Legacy(TrainingListener):
            def iteration_done(self, model, iteration, epoch):
                seen.append((iteration, float(model.score_)))

        data = _batches(4)
        a = _mlp(1)
        la = Legacy()
        a.set_listeners(la)
        a.fit(ExistingDataSetIterator(data), epochs=1)
        ref = list(seen)
        seen.clear()
        b = _mlp(4)
        b.set_listeners(Legacy())
        b.fit(ExistingDataSetIterator(data), epochs=1)
        assert [i for i, _ in seen] == [i for i, _ in ref] == [1, 2, 3, 4]
        np.testing.assert_array_equal(
            np.asarray([s for _, s in seen], np.float32),
            np.asarray([s for _, s in ref], np.float32))


class TestPrefetchAndConf:
    def test_async_device_put_and_bundle_stages(self):
        data = _batches(5)
        it = AsyncDataSetIterator(ExistingDataSetIterator(data),
                                  queue_size=2, device_put=True,
                                  bundle_size=2)
        items = list(it)
        assert [type(i).__name__ for i in items] == [
            "BatchBundle", "BatchBundle", "DeviceDataSet"]
        assert isinstance(items[0].features, jax.Array)
        assert items[0].features.shape == (2, 8, 12)
        assert isinstance(items[2].features, jax.Array)
        # reset restarts the producer with the same stages
        it.reset()
        again = list(it)
        assert [type(i).__name__ for i in again] == [
            "BatchBundle", "BatchBundle", "DeviceDataSet"]

    def test_bundled_shutdown_does_not_drain_inner(self):
        """shutdown() mid-stream must stop the bundling producer promptly
        — not let it run the inner iterator to exhaustion (it would never
        return on an unbounded stream)."""
        inner = ExistingDataSetIterator(_batches(400))
        it = AsyncDataSetIterator(inner, queue_size=1, bundle_size=4)
        assert isinstance(next(iter(it)), BatchBundle)
        it.shutdown()
        assert inner._pos < 60  # staged a few bundles, nowhere near 400

    def test_performance_listener_times_whole_bundles(self):
        """PerformanceListener under bundling measures across bundles —
        the per-step replay would divide by ~0 wall time."""
        from deeplearning4j_tpu.train.listeners import PerformanceListener

        printed = []
        net = _mlp(4)
        net.set_listeners(PerformanceListener(frequency=4,
                                              printer=printed.append))
        net.fit(ExistingDataSetIterator(_batches(12)), epochs=1)
        # first bundle seeds the clock; bundles 2 and 3 report
        assert len(printed) == 2
        for line in printed:
            rate = float(line.split(":")[1].split()[0])
            assert np.isfinite(rate) and rate > 0

    def test_bundle_unstack_roundtrip(self):
        data = _batches(3)
        bundle = BatchBundle.stack(data[:3])
        singles = bundle.unstack()
        assert len(singles) == 3
        for orig, back in zip(data, singles):
            np.testing.assert_array_equal(orig.features,
                                          np.asarray(back.features))
            np.testing.assert_array_equal(orig.labels,
                                          np.asarray(back.labels))

    def test_queue_size_configurable_via_conf(self, monkeypatch):
        captured = {}
        real = AsyncDataSetIterator

        def spy(inner, queue_size=4, **kw):
            captured["queue_size"] = queue_size
            return real(inner, queue_size=queue_size, **kw)

        import deeplearning4j_tpu.nn.multilayer as mln_mod

        monkeypatch.setattr(mln_mod, "AsyncDataSetIterator", spy)
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
                .async_queue_size(2).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(ExistingDataSetIterator(_batches(2)), epochs=1)
        assert captured["queue_size"] == 2

    def test_queue_depth_scaled_down_by_bundle_size(self, monkeypatch):
        """Each queue slot holds K batches under bundling; the slot count
        scales down so the staged-batch budget stays at the k=1 level."""
        captured = {}
        real = AsyncDataSetIterator

        def spy(inner, queue_size=4, **kw):
            captured["queue_size"] = queue_size
            captured["bundle_size"] = kw.get("bundle_size", 1)
            return real(inner, queue_size=queue_size, **kw)

        import deeplearning4j_tpu.nn.multilayer as mln_mod

        monkeypatch.setattr(mln_mod, "AsyncDataSetIterator", spy)
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
                .steps_per_call(4).async_queue_size(8).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(ExistingDataSetIterator(_batches(4)), epochs=1)
        assert captured == {"queue_size": 2, "bundle_size": 4}

    def test_conf_serde_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.builders import (
            MultiLayerConfiguration,
        )

        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
                .steps_per_call(8).async_queue_size(6).list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=2, loss="mcxent"))
                .set_input_type(InputType.feed_forward(3)).build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.global_conf.steps_per_call == 8
        assert back.global_conf.async_queue_size == 6


class TestDataParallelBundling:
    def test_parallel_wrapper_bundled_parity(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        data = _batches(5)
        a, b = _mlp(1), _mlp(2)
        ParallelWrapper(a, workers=4).fit(ExistingDataSetIterator(data))
        ParallelWrapper(b, workers=4).fit(ExistingDataSetIterator(data))
        assert a.iteration == b.iteration == 5
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)

    def test_parallel_wrapper_skips_bundling_when_always_padding(self):
        """A batch size never divisible by the data axis means no bundle
        could ever run — the wrapper clamps to k=1 up front instead of
        stacking and unstacking every bundle."""
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        data = _batches(4, b=6)  # 6 % 4 != 0: every batch padded
        a, b = _mlp(1), _mlp(2)
        pa, pb = (ParallelWrapper(a, workers=4),
                  ParallelWrapper(b, workers=4))
        pa.fit(ExistingDataSetIterator(data))
        pb.fit(ExistingDataSetIterator(data))
        assert pb._bstep is None  # bundled step never built
        _assert_trees_equal(a.params_, b.params_)

    def test_parallel_wrapper_zero1_bundled_parity(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        data = _batches(4)
        a, b = _mlp(1), _mlp(2)
        ParallelWrapper(a, workers=4, sharded_update=True).fit(
            ExistingDataSetIterator(data))
        ParallelWrapper(b, workers=4, sharded_update=True).fit(
            ExistingDataSetIterator(data))
        _assert_trees_equal(a.params_, b.params_)
        _assert_trees_equal(a.opt_state_, b.opt_state_)

    def test_shared_training_bundled_parity(self):
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.shared_training import (
            SharedTrainingMaster,
        )

        data = _batches(3)
        a, b = _mlp(1), _mlp(2)
        sa = SharedTrainingMaster(mesh=TrainingMesh(data=8))
        sb = SharedTrainingMaster(mesh=TrainingMesh(data=8))
        sa.fit(a, ExistingDataSetIterator(data), epochs=1)
        sb.fit(b, ExistingDataSetIterator(data), epochs=1)
        assert a.iteration == b.iteration == 3
        _assert_trees_equal(a.params_, b.params_)
        # the residual carry threads the scan identically
        assert sa.residual_magnitude() == sb.residual_magnitude()

    def test_lm_trainer_fit_bundle_parity(self):
        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import (
            DistributedLMTrainer,
        )

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (2, 8, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=2).astype(np.int32)

        def build():
            m = TransformerLM(vocab_size=64, d_model=16, n_heads=2,
                              n_layers=1, max_length=8).init()
            tr = DistributedLMTrainer(m, TrainingMesh(data=8),
                                      steps_per_call=2)
            tr.place()
            return m, tr

        ma, ta = build()
        mb, tb = build()
        for j in range(2):
            ta.fit_batch(ids[j], tgt[j])
        scores = tb.fit_bundle(ids, tgt)
        assert scores.shape == (2,)
        assert ma.iteration == mb.iteration == 2
        _assert_trees_equal(ma.params_, mb.params_)
        _assert_trees_equal(ma.opt_state_, mb.opt_state_)


@pytest.mark.slow
def test_bundle_storm_k16():
    """K=16 storm: a long bundled fit with a fault policy and NaN bursts
    stays bit-identical to the unbundled run."""
    data = _batches(64)
    with faults.fault_injection(nan_grad_steps=[5, 17, 18, 40]):
        a = _mlp(1, fault_policy=True)
        a.fit(ExistingDataSetIterator(data), epochs=2)
    with faults.fault_injection(nan_grad_steps=[5, 17, 18, 40]):
        b = _mlp(16, fault_policy=True)
        b.fit(ExistingDataSetIterator(data), epochs=2)
    assert a.bad_step_count == b.bad_step_count
    _assert_trees_equal(a.params_, b.params_)
    _assert_trees_equal(a.opt_state_, b.opt_state_)
