"""Hyperparameter search subsystem (tune/): spaces, ASHA math, the
vmapped population engine's bit-parity with solo training, the
crash-safe trial store, and kill-and-resume."""

import functools
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    ExistingDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.earlystopping import (
    ClassificationScoreCalculator,
    DataSetLossCalculator,
    ScoreCalculatorObjective,
)
from deeplearning4j_tpu.tune import (
    AshaScheduler,
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    IntegerParameterSpace,
    LayerWidthsSpace,
    MedianStoppingRule,
    ParameterSpace,
    SearchSpace,
    Study,
    TrialStatus,
    TrialStore,
    asha_rungs,
    grid_search,
    mlp_factory,
    population_compatible,
    random_search,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _batches(n, batch=16, d_in=8, d_out=3, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(batch, d_in)).astype(np.float32),
                    np.eye(d_out, dtype=np.float32)[
                        rng.integers(0, d_out, batch)])
            for _ in range(n)]


def _space(**extra_params):
    params = {"lr": ContinuousParameterSpace(1e-3, 1e-1, scale="log"),
              "l2": ContinuousParameterSpace(1e-5, 1e-2, scale="log")}
    params.update(extra_params)
    return SearchSpace(
        functools.partial(mlp_factory, 8, 3, widths=(16,), dropout=0.1),
        params)


def _objective(val):
    return ScoreCalculatorObjective(
        DataSetLossCalculator(ExistingDataSetIterator(val)))


def _leaves(tree):
    return jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, tree))


# ==========================================================================
# parameter spaces + generators
# ==========================================================================
class TestSpaces:
    def test_continuous_bounds_and_scales(self):
        rng = np.random.Generator(np.random.PCG64(0))
        lin = ContinuousParameterSpace(-1.0, 3.0)
        logs = ContinuousParameterSpace(1e-4, 1e-1, scale="log")
        for _ in range(200):
            assert -1.0 <= lin.sample(rng) <= 3.0
            assert 1e-4 <= logs.sample(rng) <= 1e-1 * (1 + 1e-9)
        with pytest.raises(ValueError):
            ContinuousParameterSpace(-1.0, 1.0, scale="log")
        with pytest.raises(ValueError):
            ContinuousParameterSpace(2.0, 1.0)
        g = logs.grid(4)
        assert g[0] == pytest.approx(1e-4) and g[-1] == pytest.approx(1e-1)
        # log grid is geometric, not arithmetic
        assert g[1] / g[0] == pytest.approx(g[2] / g[1])

    def test_integer_and_discrete(self):
        rng = np.random.Generator(np.random.PCG64(1))
        ispace = IntegerParameterSpace(2, 5)
        seen = {ispace.sample(rng) for _ in range(200)}
        assert seen == {2, 3, 4, 5}
        assert ispace.grid(10) == [2, 3, 4, 5]
        d = DiscreteParameterSpace(["relu", "tanh"])
        assert {d.sample(rng) for _ in range(50)} == {"relu", "tanh"}

    def test_layer_widths_nested(self):
        rng = np.random.Generator(np.random.PCG64(2))
        s = LayerWidthsSpace(IntegerParameterSpace(1, 3),
                             DiscreteParameterSpace([16, 32]))
        for _ in range(50):
            widths = s.sample(rng)
            assert isinstance(widths, tuple)
            assert 1 <= len(widths) <= 3
            assert set(widths) <= {16, 32}

    def test_random_search_reproducible_in_process(self):
        params = {"lr": ContinuousParameterSpace(1e-4, 1e-1, scale="log"),
                  "depth": IntegerParameterSpace(1, 4)}
        a = random_search(params, seed=7, n=16)
        b = random_search(params, seed=7, n=16)
        assert a == b
        assert random_search(params, seed=8, n=16) != a

    def test_random_search_bit_reproducible_across_processes(self):
        """Seeded sampling must be deterministic process-to-process —
        a resumed study regenerates the exact candidate list."""
        params = {"lr": ContinuousParameterSpace(1e-4, 1e-1, scale="log"),
                  "l2": ContinuousParameterSpace(1e-6, 1e-2, scale="log"),
                  "depth": IntegerParameterSpace(1, 4)}
        local = random_search(params, seed=123, n=8)
        code = (
            "import json\n"
            "from deeplearning4j_tpu.tune import (ContinuousParameterSpace,"
            " IntegerParameterSpace, random_search)\n"
            "params = {'lr': ContinuousParameterSpace(1e-4, 1e-1,"
            " scale='log'), 'l2': ContinuousParameterSpace(1e-6, 1e-2,"
            " scale='log'), 'depth': IntegerParameterSpace(1, 4)}\n"
            "print(json.dumps(random_search(params, seed=123, n=8)))\n"
        )
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        remote = json.loads(out.stdout.strip().splitlines()[-1])
        # exact float equality — PCG64 streams are platform-stable bits
        assert remote == json.loads(json.dumps(local))

    def test_grid_search_product_order(self):
        params = {"a": DiscreteParameterSpace([1, 2]),
                  "b": DiscreteParameterSpace(["x", "y"])}
        grid = grid_search(params, 2)
        assert grid == [{"a": 1, "b": "x"}, {"a": 1, "b": "y"},
                        {"a": 2, "b": "x"}, {"a": 2, "b": "y"}]

    def test_space_json_roundtrip(self):
        space = _space(widths=LayerWidthsSpace(
            IntegerParameterSpace(1, 2), DiscreteParameterSpace([16, 32])))
        params2 = SearchSpace.params_from_json(space.params_to_json())
        assert params2 == space.params
        with pytest.raises(ValueError):
            ParameterSpace.from_dict({"type": "nope"})


# ==========================================================================
# ASHA + median rule — hand-computed brackets
# ==========================================================================
class TestAsha:
    def test_rung_ladder(self):
        assert asha_rungs(2, 16, 2) == [2, 4, 8, 16]
        assert asha_rungs(3, 81, 3) == [3, 9, 27, 81]
        # cap: max_budget always terminates the ladder
        assert asha_rungs(4, 10, 2) == [4, 8, 10]
        assert asha_rungs(5, 5, 2) == [5]
        with pytest.raises(ValueError):
            asha_rungs(0, 10, 2)
        with pytest.raises(ValueError):
            asha_rungs(2, 10, 1)

    def test_select_survivors_hand_computed(self):
        s = AshaScheduler(2, 8, eta=2, minimize=True)  # rungs [2, 4, 8]
        scored = [("t0", 0.9), ("t1", 0.1), ("t2", 0.5), ("t3", 0.3),
                  ("t4", 0.7), ("t5", 0.2), ("t6", 0.8), ("t7", 0.4)]
        # n=8, eta=2 -> keep 4 best (lowest): t1 .1, t5 .2, t3 .3, t7 .4
        assert sorted(s.select_survivors(0, scored)) == \
            ["t1", "t3", "t5", "t7"]
        # n=3 -> keep 1; tie broken toward the smaller trial id
        assert s.select_survivors(1, [("b", 0.2), ("a", 0.2),
                                      ("c", 0.5)]) == ["a"]
        # final rung keeps everyone
        assert s.select_survivors(2, [("a", 9.0), ("b", 1.0)]) == \
            ["a", "b"]
        # maximize flips the direction
        smax = AshaScheduler(2, 8, eta=2, minimize=False)
        assert sorted(smax.select_survivors(0, scored)) == \
            ["t0", "t2", "t4", "t6"]

    def test_async_report_quantile_rule(self):
        s = AshaScheduler(2, 8, eta=2, minimize=True)
        # first reporter at a rung always survives (cutoff = own score)
        assert s.report("a", 0, 0.5) == "promote"
        # 0.9 vs scores [0.5, 0.9]: median cutoff 0.7 -> stop
        assert s.report("b", 0, 0.9) == "stop"
        # 0.4 vs [0.5, 0.9, 0.4]: cutoff quantile(0.5)=0.5 -> promote
        assert s.report("c", 0, 0.4) == "promote"
        # final rung completes regardless of rank
        assert s.report("a", 2, 99.0) == "complete"
        assert s.report("d", 0, float("nan")) == "stop"

    def test_median_stopping_rule(self):
        m = MedianStoppingRule(grace=1, min_reports=3, minimize=True)
        # rung 0 is inside the grace window: never stops
        assert m.report("a", 0, 9.9) == "continue"
        for tid, sc in [("a", 0.1), ("b", 0.2), ("c", 0.3)]:
            assert m.report(tid, 1, sc) == "continue"  # building quorum
        # median of [0.1, 0.2, 0.3] = 0.2; 0.25 is worse -> stop
        assert m.report("d", 1, 0.25) == "stop"
        assert m.report("e", 1, 0.15) == "continue"
        # a NaN score stops outright and must NOT poison the rung median
        assert m.report("f", 1, float("nan")) == "stop"
        assert m.report("g", 1, 0.12) == "continue"


# ==========================================================================
# trial store
# ==========================================================================
class TestStore:
    def test_append_replay_reconstruct(self, tmp_path):
        st = TrialStore(str(tmp_path))
        st.write_meta({"seed": 1})
        st.append({"kind": "trial", "id": "t0", "overrides": {"lr": 0.1},
                   "seed": 5})
        st.append({"kind": "rung", "id": "t0", "rung": 0, "score": 1.5})
        st.append({"kind": "status", "id": "t0", "status": "COMPLETED"})
        assert st.read_meta() == {"seed": 1}
        trials, records = st.reconstruct()
        assert len(records) == 3
        t = trials["t0"]
        assert t.status == TrialStatus.COMPLETED
        assert t.rung == 0 and t.scores == {0: 1.5}
        assert t.overrides == {"lr": 0.1} and t.seed == 5

    def test_torn_tail_dropped_torn_middle_raises(self, tmp_path):
        st = TrialStore(str(tmp_path))
        st.append({"kind": "trial", "id": "t0", "seed": 1})
        st.append({"kind": "rung", "id": "t0", "rung": 0, "score": 2.0})
        # crash truncation: chop the last line mid-record
        with open(st.journal_path) as f:
            content = f.read()
        with open(st.journal_path, "w") as f:
            f.write(content[: len(content) - 9])
        with pytest.warns(UserWarning, match="torn trailing line"):
            records = st.replay()
        assert [r["kind"] for r in records] == ["trial"]
        # corruption in the MIDDLE is not crash truncation: refuse
        with open(st.journal_path, "w") as f:
            f.write('{"kind": "trial", "id": "t0", "seed": 1}\n'
                    '{"kind": "ru\n'
                    '{"kind": "status", "id": "t0", "status": "STOPPED"}\n')
        with pytest.raises(ValueError, match="corrupt journal"):
            st.replay()


# ==========================================================================
# population engine: legality + bit parity with solo training
# ==========================================================================
class TestPopulationEngine:
    def test_population_compatible_and_fallback_reason(self):
        space = _space()
        confs = [space.build(ov, seed=100 + i) for i, ov in
                 enumerate(space.candidates(num_trials=3, seed=0))]
        ok, reason = population_compatible(confs)
        assert ok, reason
        het = SearchSpace(
            functools.partial(mlp_factory, 8, 3),
            {"widths": DiscreteParameterSpace([(16,), (32,)]),
             "lr": ContinuousParameterSpace(1e-3, 1e-1, scale="log")})
        confs = [het.build({"widths": (16,), "lr": 0.01}, seed=1),
                 het.build({"widths": (32,), "lr": 0.01}, seed=2)]
        ok, reason = population_compatible(confs)
        assert not ok and "pool engine" in reason

    def test_momentum_difference_is_not_vmappable(self):
        """Only the learning-rate FixedSchedule is cell-rebindable;
        trials differing in another fixed scalar schedule (Nesterovs
        momentum) must NOT stack — the population engine would silently
        train every trial with trial 0's momentum."""
        from deeplearning4j_tpu.nn.conf.builders import (
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers.core import (
            DenseLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.updaters import Nesterovs

        def conf(momentum, lr=0.05):
            return (NeuralNetConfiguration.builder().seed(1)
                    .updater(Nesterovs(lr, momentum=momentum)).list()
                    .layer(DenseLayer(n_out=16, activation="relu"))
                    .layer(OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.feed_forward(8)).build())

        ok, _ = population_compatible([conf(0.9), conf(0.5)])
        assert not ok
        # same momentum, different lr: still stackable
        ok, reason = population_compatible(
            [conf(0.9, lr=0.05), conf(0.9, lr=0.01)])
        assert ok, reason

    def test_population_bit_parity_with_solo_runs(self):
        """Acceptance core: every trial of an N=8 vmapped population
        (steps_per_call bundling on) ends with params AND Adam slots
        bit-identical to training that trial alone with the same seed
        over the same batch schedule."""
        import jax.numpy as jnp

        train = _batches(10)
        val = _batches(3, seed=99)
        space = _space()
        # single-rung ladder: no trial gets stopped, all reach 13 steps
        # (13 = 3 full K=4 bundles + a remainder chunk)
        study = Study(space, train, _objective(val),
                      scheduler=AshaScheduler(13, 13, eta=2),
                      num_trials=8, seed=42, engine="population",
                      steps_per_call=4)
        result = study.run()
        assert result.engine == "population"
        assert all(t.status == TrialStatus.COMPLETED
                   for t in result.trials)

        # rebuild every trial solo through the stock fit machinery
        pop_models = {t.id: m for t, m in
                      zip(result.trials,
                          [None] * len(result.trials))}
        # population models are internal; re-run the study's own solo
        # path: build from the same conf/seed and step through the same
        # batch schedule one dispatch at a time
        for trial in result.trials:
            conf = space.build(trial.overrides, seed=trial.seed)
            solo = MultiLayerNetwork(conf).init()
            step = solo._get_jit("train", solo._make_train_step)
            for s in range(13):
                solo._fit_batch(step, train[s % len(train)])
            # identical rung score...
            score = _objective(val)(solo)
            assert score == trial.scores[0], (trial.id, score,
                                              trial.scores[0])
            pop_models[trial.id] = solo
        # ...and for the best trial the study exposes the trained model:
        # bit-compare params + updater slots against its solo twin
        best = result.best_trial
        solo = pop_models[best.id]
        for a, b in zip(_leaves(result.best_model.params_),
                        _leaves(solo.params_)):
            assert np.array_equal(a, b)
        for a, b in zip(_leaves(result.best_model.opt_state_),
                        _leaves(solo.opt_state_)):
            assert np.array_equal(a, b)

    def test_asha_study_lifecycle_accounting(self):
        """eta=2, N=4, rungs [4, 8, 16]: rung 0 stops 2, rung 1 stops 1,
        the last survivor completes — every trial in a terminal state."""
        train = _batches(8)
        val = _batches(2, seed=9)
        study = Study(_space(), train, _objective(val),
                      scheduler=AshaScheduler(4, 16, eta=2),
                      num_trials=4, seed=7, engine="population",
                      steps_per_call=4)
        result = study.run()
        statuses = sorted(t.status for t in result.trials)
        assert statuses == [TrialStatus.COMPLETED, TrialStatus.STOPPED,
                            TrialStatus.STOPPED, TrialStatus.STOPPED]
        done = [t for t in result.trials
                if t.status == TrialStatus.COMPLETED]
        assert done[0].rung == 2 and set(done[0].scores) == {0, 1, 2}
        assert result.best_trial is done[0]

    def test_heterogeneous_space_auto_falls_back_to_pool(self):
        train = _batches(6)
        val = _batches(2, seed=9)
        het = SearchSpace(
            functools.partial(mlp_factory, 8, 3),
            {"widths": DiscreteParameterSpace([(8,), (12,)]),
             "lr": ContinuousParameterSpace(1e-3, 1e-1, scale="log")})
        study = Study(het, train, _objective(val),
                      scheduler=AshaScheduler(4, 4, eta=2),
                      num_trials=3, seed=1, engine="auto", workers=3)
        result = study.run()
        assert result.engine == "pool"
        assert all(t.status == TrialStatus.COMPLETED
                   for t in result.trials)
        assert result.best_trial is not None
        # requesting the population engine outright for these is an error
        with pytest.raises(ValueError, match="not stackable"):
            Study(het, train, _objective(val),
                  scheduler=AshaScheduler(4, 4, eta=2), num_trials=3,
                  seed=1, engine="population").run()


# ==========================================================================
# kill-and-resume
# ==========================================================================
def _study_kwargs(store_dir):
    return dict(scheduler=AshaScheduler(6, 24, eta=2), num_trials=4,
                seed=11, engine="population", steps_per_call=2,
                store_dir=store_dir, keep_last=2)


class TestResume:
    def test_completed_study_resume_is_a_noop(self, tmp_path):
        train = _batches(8)
        val = _batches(2, seed=9)
        store_dir = str(tmp_path / "study")
        r1 = Study(_space(), train, _objective(val),
                   **_study_kwargs(store_dir)).run()
        journal_size = os.path.getsize(
            os.path.join(store_dir, "trials.jsonl"))
        r2 = Study(_space(), train, _objective(val),
                   **_study_kwargs(store_dir)).run(resume=True)
        # nothing retrained, nothing re-journaled, same winner
        assert os.path.getsize(
            os.path.join(store_dir, "trials.jsonl")) == journal_size
        assert [t.status for t in r1.trials] == \
            [t.status for t in r2.trials]
        assert r1.best_trial.id == r2.best_trial.id
        assert r1.best_trial.final_score == r2.best_trial.final_score

    def test_resume_rejects_foreign_store(self, tmp_path):
        train = _batches(8)
        val = _batches(2, seed=9)
        store_dir = str(tmp_path / "study")
        Study(_space(), train, _objective(val),
              **_study_kwargs(store_dir)).run()
        other = _study_kwargs(store_dir)
        other["scheduler"] = AshaScheduler(5, 20, eta=2)
        with pytest.raises(ValueError, match="different study"):
            Study(_space(), train, _objective(val), **other).run(
                resume=True)

    def test_sigkill_mid_study_then_resume_completes(self, tmp_path):
        """The acceptance drill: SIGKILL a study mid-flight, restart
        with resume — it completes with every trial accounted for, no
        duplicated trial ids, and no checkpoints beyond keep-last-k."""
        store_dir = str(tmp_path / "study")
        child_src = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator, ScoreCalculatorObjective)
from deeplearning4j_tpu.tune import (AshaScheduler,
    ContinuousParameterSpace, SearchSpace, Study, mlp_factory)
import functools, sys, time

def batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(16, 8)).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])
            for _ in range(n)]

space = SearchSpace(
    functools.partial(mlp_factory, 8, 3, widths=(16,), dropout=0.1),
    {{"lr": ContinuousParameterSpace(1e-3, 1e-1, scale="log"),
      "l2": ContinuousParameterSpace(1e-5, 1e-2, scale="log")}})
obj = ScoreCalculatorObjective(
    DataSetLossCalculator(ExistingDataSetIterator(batches(2, seed=9))))
study = Study(space, batches(8), obj,
              scheduler=AshaScheduler(6, 24, eta=2), num_trials=4,
              seed=11, engine="population", steps_per_call=2,
              store_dir={store_dir!r}, keep_last=2)
study.run()
print("CHILD_DONE", flush=True)
time.sleep(120)  # hold the process so the parent always gets its kill in
"""
        env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        journal = os.path.join(store_dir, "trials.jsonl")
        try:
            # wait for mid-study evidence: at least one rung record
            deadline = time.time() + 240
            while time.time() < deadline:
                if os.path.exists(journal) and any(
                        '"kind": "rung"' in ln
                        for ln in open(journal)):
                    break
                if proc.poll() is not None:
                    pytest.fail("child exited before first rung: "
                                + (proc.stdout.read() or ""))
                time.sleep(0.05)
            else:
                pytest.fail("no rung record before deadline")
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)

        # resume in this process with the identical study definition
        train = _batches(8)
        val = _batches(2, seed=9)
        result = Study(_space(), train, _objective(val),
                       **_study_kwargs(store_dir)).run(resume=True)
        assert all(t.is_terminal() for t in result.trials)
        assert result.best_trial is not None

        store = TrialStore(store_dir)
        _, records = store.reconstruct()
        trial_ids = [r["id"] for r in records if r["kind"] == "trial"]
        assert len(trial_ids) == len(set(trial_ids)) == 4
        # each trial accounted: exactly one terminal status per trial
        finals = {}
        for r in records:
            if r["kind"] == "status":
                assert r["id"] not in finals, f"double finish: {r}"
                finals[r["id"]] = r["status"]
        assert set(finals) == set(trial_ids)
        # retention: no trial dir holds more than keep_last checkpoints
        for tid in trial_ids:
            assert len(store.trial_checkpoints(tid)) <= 2


# ==========================================================================
# score-calculator determinism (satellite)
# ==========================================================================
class TestScoreCalculatorReset:
    def _model(self):
        conf = mlp_factory(8, 3, lr=1e-2, widths=(8,))
        return MultiLayerNetwork(conf).init()

    def test_repeat_evaluation_is_deterministic(self):
        model = self._model()
        ds = _batches(1, batch=32)[0]
        it = ListDataSetIterator(ds, 8)
        calc = DataSetLossCalculator(it)
        first = calc.calculate_score(model)
        assert calc.calculate_score(model) == first
        # even after someone leaves the shared iterator mid-stream
        it.next()
        assert calc.calculate_score(model) == first

    def test_classification_calculator_resets_between_calls(self):
        model = self._model()
        ds = _batches(1, batch=32)[0]
        it = ListDataSetIterator(ds, 8)
        calc = ClassificationScoreCalculator("accuracy", it)
        first = calc.calculate_score(model)
        it.next()  # partially consume between calls
        assert calc.calculate_score(model) == first


# ==========================================================================
# storms (slow tier)
# ==========================================================================
@pytest.mark.slow
def test_population_storm_n16_k8():
    """16-trial population, K=8 bundling, three-rung ASHA — the stacked
    program at width 16 stays bit-stable (scores finite, accounting
    closed) under a bigger cohort than the fast tests use."""
    train = _batches(16, batch=32)
    val = _batches(3, seed=5, batch=32)
    study = Study(_space(), train, _objective(val),
                  scheduler=AshaScheduler(8, 32, eta=2),
                  num_trials=16, seed=3, engine="population",
                  steps_per_call=8)
    result = study.run()
    assert all(t.is_terminal() for t in result.trials)
    done = [t for t in result.trials if t.status == TrialStatus.COMPLETED]
    assert done and all(np.isfinite(t.final_score) for t in done)
