"""Invariant-linter tests (deeplearning4j_tpu/analysis): one positive
fixture (violation detected, correct file:line) and one negative
fixture (idiomatic code passes) per rule engine, baseline add/expire
semantics, the four acceptance defect-class seeds, and THE tier-1
gate: the shipped tree is lint-clean against the shipped baseline."""

import json
import os
import textwrap

import pytest

import deeplearning4j_tpu
from deeplearning4j_tpu.analysis import run_lint
from deeplearning4j_tpu.analysis.baseline import (
    apply_baseline,
    load_baseline,
    write_baseline,
)
from deeplearning4j_tpu.analysis.core import lint_paths

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(deeplearning4j_tpu.__file__)))


def write(tmp_path, rel, body):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return str(p)


def findings_for(tmp_path, rel, body, rule=None):
    write(tmp_path, rel, body)
    fs = lint_paths(str(tmp_path))
    return [f for f in fs if rule is None or f.rule == rule]


# ==========================================================================
# rule engines: positive + negative fixtures
# ==========================================================================
class TestDurabilityRules:
    def test_unsynced_replace_detected_with_line(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/writer.py", """\
            import os

            def publish(tmp, dst):
                with open(tmp, "w") as f:
                    f.write("x")
                os.replace(tmp, dst)
            """, rule="durability-unsynced-replace")
        assert len(fs) == 1
        assert fs[0].path == "pkg/writer.py"
        assert fs[0].line == 6  # the os.replace line, exactly

    def test_fsynced_replace_passes(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/writer.py", """\
            import os

            def publish(tmp, dst):
                with open(tmp, "w") as f:
                    f.write("x")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, dst)
            """, rule="durability-unsynced-replace")
        assert fs == []

    def test_fslayer_helpers_count_as_barrier(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/writer.py", """\
            import os
            from deeplearning4j_tpu.chaos import fslayer

            def publish(tmp, dst):
                fslayer.fsync_path(tmp, surface="checkpoint")
                os.replace(tmp, dst)
            """, rule="durability-unsynced-replace")
        assert fs == []

    def test_bypass_fslayer_on_durable_surface(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/serving/store.py", """\
            def save(path):
                with open(path, "w") as f:
                    f.write("x")
            """, rule="durability-bypass-fslayer")
        assert len(fs) == 1
        assert fs[0].line == 2

    def test_os_open_write_flags_on_durable_surface(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/serving/journal.py", """\
            import os

            def append(path, line):
                fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT)
                try:
                    os.write(fd, line)
                finally:
                    os.close(fd)
            """, rule="durability-bypass-fslayer")
        assert len(fs) == 1
        assert fs[0].line == 4  # the os.open line, exactly
        assert "os.open" in fs[0].message

    def test_os_open_readonly_passes(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/serving/reader.py", """\
            import os

            def read(path):
                fd = os.open(path, os.O_RDONLY)
                try:
                    return os.read(fd, 1 << 16)
                finally:
                    os.close(fd)
            """, rule="durability-bypass-fslayer")
        assert fs == []

    def test_reads_and_nondurable_dirs_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/serving/loader.py", """\
            def load(path):
                with open(path) as f:
                    return f.read()
            """, rule="durability-bypass-fslayer")
        assert fs == []
        fs = findings_for(tmp_path, "pkg/ui/report.py", """\
            def save(path):
                with open(path, "w") as f:
                    f.write("x")
            """, rule="durability-bypass-fslayer")
        assert fs == []


class TestTypedErrorRules:
    def test_bare_keyerror_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/registry.py", """\
            def get(d, k):
                if k not in d:
                    raise KeyError(f"unknown {k}")
                return d[k]
            """, rule="typed-errors-bare-raise")
        assert len(fs) == 1 and fs[0].line == 3

    def test_subclass_and_protocol_raises_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/registry.py", """\
            class UnknownThingError(KeyError):
                pass

            def get(d, k):
                if k not in d:
                    raise UnknownThingError(k)
                return d[k]

            class Proxy:
                def __getattr__(self, name):
                    raise AttributeError(name)

                @property
                def params(self):
                    raise AttributeError("use total_params()")

            class It:
                def next(self):
                    raise StopIteration
            """, rule="typed-errors-bare-raise")
        assert fs == []

    def test_broad_except_without_ack_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/worker.py", """\
            def run(fn):
                try:
                    fn()
                except Exception:
                    pass
            """, rule="typed-errors-broad-except")
        assert len(fs) == 1 and fs[0].line == 4

    def test_ack_comment_reraise_and_narrow_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/worker.py", """\
            def run(fn):
                try:
                    fn()
                except Exception:  # noqa: BLE001 — fn is user code
                    pass
                try:
                    fn()
                except Exception as e:
                    raise RuntimeError("typed") from e
                try:
                    fn()
                except ValueError:
                    pass
            """, rule="typed-errors-broad-except")
        assert fs == []

    def test_bare_except_flagged_even_with_comment(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/worker.py", """\
            def run(fn):
                try:
                    fn()
                except:  # noqa
                    pass
            """, rule="typed-errors-broad-except")
        assert len(fs) == 1
        assert "SystemExit" in fs[0].message


class TestTraceSafetyRules:
    def test_host_sync_in_jitted_body_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/stepper.py", """\
            import jax

            def make_step():
                def step(params, batch):
                    loss = compute(params, batch)
                    print(float(loss))
                    return loss
                return jax.jit(step)
            """, rule="trace-host-sync")
        assert len(fs) == 1 and fs[0].line == 6

    def test_item_in_decorated_jit_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/stepper.py", """\
            from functools import partial
            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def step(params, batch):
                return params * batch.loss.item()
            """, rule="trace-host-sync")
        assert len(fs) == 1 and fs[0].line == 6

    def test_shape_math_and_unjitted_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/stepper.py", """\
            import jax

            def make_step():
                def step(params, batch):
                    scale = float(batch.shape[0])
                    return params * scale
                return jax.jit(step)

            def host_helper(x):
                return float(x)  # not jitted: host code is free
            """, rule="trace-host-sync")
        assert fs == []

    def test_probe_jnp_inputs_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/nn/ops/kern.py", """\
            import jax.numpy as jnp

            def _probe_kern(n):
                x = jnp.ones((n, n))
                return x
            """, rule="trace-probe-jnp")
        assert len(fs) == 1 and fs[0].line == 4

    def test_probe_numpy_inputs_and_non_ops_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/nn/ops/kern.py", """\
            import numpy as np

            def _probe_kern(n):
                return np.ones((n, n), np.float32)
            """, rule="trace-probe-jnp")
        assert fs == []
        fs = findings_for(tmp_path, "pkg/models/thing.py", """\
            import jax.numpy as jnp

            def probe_data(n):
                return jnp.ones((n,))
            """, rule="trace-probe-jnp")
        assert fs == []


class TestEventSchemaRule:
    def test_undeclared_event_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/sys.py", """\
            from deeplearning4j_tpu.obs import flight as _flight

            def work():
                _flight.record("definitely_not_declared_xyz", a=1)
            """, rule="event-schema")
        assert len(fs) == 1 and fs[0].line == 4
        assert "definitely_not_declared_xyz" in fs[0].message

    def test_undeclared_fire_point_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/sys.py", """\
            from deeplearning4j_tpu.chaos import hooks as chaos_hooks

            def work():
                chaos_hooks.fire("bogus.seam_point")
            """, rule="event-schema")
        assert len(fs) == 1

    def test_declared_names_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/sys.py", """\
            from deeplearning4j_tpu.chaos import hooks as chaos_hooks
            from deeplearning4j_tpu.obs import flight as _flight

            def work():
                _flight.record("checkpoint_write", path="p")
                chaos_hooks.fire("fs.replace", path="p", surface="s")
            """, rule="event-schema")
        assert fs == []


class TestAlertSchemaRule:
    def test_undeclared_alert_name_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/watch.py", """\
            from deeplearning4j_tpu.obs.alerts import AlertRule

            RULES = [
                AlertRule("totally_made_up_alert", "threshold",
                          metric="g"),
            ]
            """, rule="alert-schema")
        assert len(fs) == 1 and fs[0].line == 4
        assert "totally_made_up_alert" in fs[0].message

    def test_declared_and_attribute_ctor_pass(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/watch.py", """\
            from deeplearning4j_tpu.obs import alerts

            RULES = [
                alerts.AlertRule("nan_step_storm", "increase",
                                 metric="flight_events_total",
                                 labels={"kind": "nan_skip"}),
            ]
            """, rule="alert-schema")
        assert fs == []

    def test_attribute_ctor_undeclared_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/watch.py", """\
            from deeplearning4j_tpu.obs import alerts

            RULES = [alerts.AlertRule("nope_never", "threshold",
                                      metric="g")]
            """, rule="alert-schema")
        assert len(fs) == 1


class TestControllerVerdictRule:
    def test_bare_action_call_detected_with_line(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/knobs.py", """\
            def squeeze(batcher):
                batcher.set_max_wait_ms(1.0)
            """, rule="controller-verdict-attached")
        assert len(fs) == 1
        assert fs[0].line == 2
        assert "set_max_wait_ms" in fs[0].message

    def test_verdict_carrying_record_passes(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/knobs.py", """\
            from deeplearning4j_tpu.obs import flight as _flight

            def squeeze(batcher, verdict):
                batcher.set_max_wait_ms(1.0)
                _flight.record("controller_retune", action="shrink",
                               verdict=verdict.status)
            """, rule="controller-verdict-attached")
        assert fs == []

    def test_controller_record_without_verdict_detected(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/knobs.py", """\
            from deeplearning4j_tpu.obs import flight as _flight

            def squeeze(batcher):
                batcher.set_max_wait_ms(1.0)
                _flight.record("controller_retune", action="shrink")
            """, rule="controller-verdict-attached")
        # two findings: the verdict-less record AND the action call it
        # fails to attribute
        assert sorted(f.line for f in fs) == [4, 5]
        assert any("verdict=" in f.message for f in fs)

    def test_lambda_defers_the_action(self, tmp_path):
        # building an actuator is not taking an action — the deferred
        # call is attributed where the lambda is eventually invoked
        fs = findings_for(tmp_path, "pkg/wire.py", """\
            def actuator(router, model):
                return lambda n: router.scale_generation_slots(model, n)
            """, rule="controller-verdict-attached")
        assert fs == []

    def test_action_methods_themselves_exempt(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/router.py", """\
            class Router:
                def demote_tenant(self, tenant, quota):
                    self._quotas[tenant] = quota

                def restore_tenant(self, tenant):
                    self.demote_tenant(tenant, None)
            """, rule="controller-verdict-attached")
        assert fs == []


class TestParseError:
    def test_unparseable_file_is_a_finding(self, tmp_path):
        fs = findings_for(tmp_path, "pkg/broken.py",
                          "def broken(:\n", rule="parse-error")
        assert len(fs) == 1


# ==========================================================================
# baseline add / expire semantics
# ==========================================================================
class TestBaseline:
    BODY = """\
        import os

        def publish(tmp, dst):
            os.replace(tmp, dst)
        """

    def test_add_suppresses_exactly_that_finding(self, tmp_path):
        write(tmp_path, "pkg/w.py", self.BODY)
        bl = str(tmp_path / "BASELINE.json")
        fs = lint_paths(str(tmp_path))
        write_baseline(bl, fs, {f.fingerprint: "legacy" for f in fs})
        rep = run_lint(str(tmp_path), baseline_path=bl)
        assert rep.ok and rep.exit_code == 0
        assert len(rep.suppressed) == len(fs) and not rep.active

        # a NEW violation is not covered by the old baseline
        write(tmp_path, "pkg/w2.py", self.BODY)
        rep = run_lint(str(tmp_path), baseline_path=bl)
        assert not rep.ok
        assert {f.path for f in rep.active} == {"pkg/w2.py"}

    def test_expire_stale_entry_fails_gate(self, tmp_path):
        write(tmp_path, "pkg/w.py", self.BODY)
        bl = str(tmp_path / "BASELINE.json")
        fs = lint_paths(str(tmp_path))
        write_baseline(bl, fs, {f.fingerprint: "legacy" for f in fs})
        # fix the violation: the baseline entry must go stale and FAIL
        write(tmp_path, "pkg/w.py", """\
            import os

            def publish(tmp, dst):
                os.fsync(0)
                os.replace(tmp, dst)
            """)
        rep = run_lint(str(tmp_path), baseline_path=bl)
        assert not rep.ok and rep.exit_code == 1
        assert len(rep.stale) == 1 and not rep.active
        assert "matched nothing" in rep.format()

    def test_fingerprint_survives_line_moves(self, tmp_path):
        write(tmp_path, "pkg/w.py", self.BODY)
        fp0 = lint_paths(str(tmp_path))[0].fingerprint
        # unrelated code above moves the finding down 3 lines
        write(tmp_path, "pkg/w.py", "X = 1\nY = 2\nZ = 3\n"
              + textwrap.dedent(self.BODY))
        fp1 = lint_paths(str(tmp_path))[0].fingerprint
        assert fp0 == fp1

    def test_versioned_and_malformed_baseline_fail_typed(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"version": 99, "entries": []}))
        with pytest.raises(ValueError):
            load_baseline(str(p))
        p.write_text(json.dumps({"entries": [{"no_fp": 1}]}))
        with pytest.raises(ValueError):
            load_baseline(str(p))

    def test_apply_baseline_occurrence_granularity(self, tmp_path):
        # two IDENTICAL violations: one baseline entry suppresses one
        write(tmp_path, "pkg/w.py", """\
            import os

            def a(t, d):
                os.replace(t, d)

            def b(t, d):
                os.replace(t, d)
            """)
        fs = [f for f in lint_paths(str(tmp_path))
              if f.rule == "durability-unsynced-replace"]
        assert len(fs) == 2
        assert fs[0].fingerprint != fs[1].fingerprint
        active, suppressed, stale = apply_baseline(
            fs, [{"fingerprint": fs[0].fingerprint}])
        assert len(active) == 1 and len(suppressed) == 1 and not stale


# ==========================================================================
# the acceptance seeds: each defect class flips the gate non-zero
# ==========================================================================
SEEDS = {
    "durability-unsynced-replace": (
        "pkg/train/ckpt.py", 4,
        "import os\n\n"
        "def publish(t, d):\n"
        "    os.replace(t, d)\n"),
    "typed-errors-bare-raise": (
        "pkg/serving/router.py", 3,
        "def pick(d, k):\n"
        "    if k not in d:\n"
        "        raise KeyError(k)\n"
        "    return d[k]\n"),
    "trace-host-sync": (
        "pkg/train/steps.py", 5,
        "import jax\n\n"
        "def make():\n"
        "    def step(p, b):\n"
        "        return p * float(b.sum())\n"
        "    return jax.jit(step)\n"),
    "event-schema": (
        "pkg/obs_bits.py", 4,
        "from deeplearning4j_tpu.obs import flight as _flight\n\n"
        "def w():\n"
        "    _flight.record(\"never_declared_event_q\")\n"),
    "controller-verdict-attached": (
        "pkg/loadgen/knobs.py", 2,
        "def squeeze(batcher):\n"
        "    batcher.set_max_wait_ms(1.0)\n"),
}


@pytest.mark.parametrize("rule", sorted(SEEDS))
def test_seeded_defect_flips_nonzero_with_file_line(tmp_path, rule):
    rel, line, body = SEEDS[rule]
    write(tmp_path, rel, body)
    rep = run_lint(str(tmp_path))
    assert rep.exit_code == 1
    hits = [f for f in rep.active if f.rule == rule]
    assert len(hits) == 1
    assert hits[0].path == rel and hits[0].line == line


# ==========================================================================
# the tier-1 gate: the shipped tree is clean vs the shipped baseline
# ==========================================================================
def test_shipped_tree_is_lint_clean_vs_baseline():
    """THE gate every future PR inherits: zero active findings, zero
    stale baseline entries over deeplearning4j_tpu/ with
    LINT_BASELINE.json. A new violation of any codified defect class
    fails THIS test with its file:line in the message."""
    pkg = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))
    rep = run_lint(REPO_ROOT, [pkg],
                   baseline_path=os.path.join(REPO_ROOT,
                                              "LINT_BASELINE.json"))
    assert rep.ok, "\n" + rep.format()


def test_cli_lint_json_roundtrip(tmp_path, capsys):
    from deeplearning4j_tpu import cli

    rc = cli.main(["lint", "--json"])
    out = capsys.readouterr().out
    body = json.loads(out)
    assert rc == 0 and body["ok"] is True
    assert body["counts"]["active"] == 0

    # seeded tree through the CLI surface: non-zero + file:line printed
    write(tmp_path, "pkg/train/ckpt.py",
          SEEDS["durability-unsynced-replace"][2])
    rc = cli.main(["lint", "--root", str(tmp_path), "--no-baseline",
                   str(tmp_path / "pkg")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "pkg/train/ckpt.py:4" in out


def test_cli_write_baseline_preserves_suppressed_entries(tmp_path,
                                                         capsys):
    """Review regression: pointing --write-baseline at the live
    baseline must carry the already-triaged entries (and their
    reviewed reasons) forward, not discard them for the active-only
    set."""
    from deeplearning4j_tpu import cli

    write(tmp_path, "pkg/a.py", TestBaseline.BODY)
    bl = str(tmp_path / "BASELINE.json")
    rc = cli.main(["lint", "--root", str(tmp_path), "--no-baseline",
                   "--write-baseline", bl, str(tmp_path / "pkg")])
    assert rc == 0
    body = json.load(open(bl))
    body["entries"][0]["reason"] = "reviewed: legacy writer"
    (tmp_path / "BASELINE.json").write_text(json.dumps(body))
    # a second violation appears; regenerate against the live baseline
    write(tmp_path, "pkg/b.py", TestBaseline.BODY)
    rc = cli.main(["lint", "--root", str(tmp_path), "--baseline", bl,
                   "--write-baseline", bl, str(tmp_path / "pkg")])
    capsys.readouterr()
    assert rc == 0
    entries = load_baseline(bl)
    assert len(entries) == 2  # old entry kept, new one added
    by_path = {e["path"]: e for e in entries}
    assert by_path["pkg/a.py"]["reason"] == "reviewed: legacy writer"
    assert "TODO" in by_path["pkg/b.py"]["reason"]


def test_events_table_matches_architecture_doc():
    """The ARCHITECTURE flight-event table is generated from
    obs/events.py — the docs cannot drift from the declared schema."""
    from deeplearning4j_tpu.analysis.tables import render_event_table

    arch = open(os.path.join(REPO_ROOT, "ARCHITECTURE.md")).read()
    assert render_event_table() in arch


# ==========================================================================
# regression tests for the findings this PR fixed (satellite 1)
# ==========================================================================
class TestFixedFindings:
    def test_flight_dump_fsyncs_before_replace(self, tmp_path,
                                               monkeypatch):
        """obs/flight.py dump(): the black box must be fsynced before
        its atomic rename (a dump that evaporates on power loss is
        worthless exactly when it is needed)."""
        import os as _os

        from deeplearning4j_tpu.obs.flight import FlightRecorder

        synced = []
        real = _os.fsync
        monkeypatch.setattr(_os, "fsync",
                            lambda fd: (synced.append(fd), real(fd))[1])
        rec = FlightRecorder(dump_dir=str(tmp_path))
        rec.record("step", iteration=1, epoch=0)
        path = rec.dump(reason="test")
        assert path and _os.path.exists(path)
        assert synced, "dump() never fsynced the staged file"
        body = json.load(open(path))
        assert body["events"][0]["kind"] == "step"

    def test_zoo_download_promote_fsyncs(self, tmp_path, monkeypatch):
        """models/zoo.py: the downloaded .part is fsynced before both
        atomic promotes."""
        import os as _os

        from deeplearning4j_tpu.models import zoo

        part = tmp_path / "w.bin.part"
        part.write_bytes(b"payload")
        synced = []
        real = _os.fsync
        monkeypatch.setattr(_os, "fsync",
                            lambda fd: (synced.append(fd), real(fd))[1])
        zoo._fsync_path(str(part))
        assert len(synced) == 1

    def test_unknown_config_class_typed(self):
        from deeplearning4j_tpu.nn.conf import serde

        with pytest.raises(serde.UnknownConfigClassError) as ei:
            serde.lookup("NoSuchConfigClass")
        assert isinstance(ei.value, KeyError)  # dict-compat preserved

    def test_unknown_zoo_model_typed(self):
        from deeplearning4j_tpu.models.selector import (
            ModelSelector,
            UnknownZooModelError,
        )

        with pytest.raises(UnknownZooModelError):
            ModelSelector.select("no-such-model")

    def test_unknown_session_typed(self):
        from deeplearning4j_tpu.ui.dashboard import (
            UIServer,
            UnknownSessionError,
        )

        srv = UIServer()
        with pytest.raises(UnknownSessionError):
            srv._find("nope")
