"""Shared synthetic labelled corpus for the distributed-ParagraphVectors
parity test: documents drawn from two disjoint word topics, so any
correct doc2vec run embeds same-topic documents far closer than
cross-topic ones. Deterministic — every process builds the identical
document list (the broadcast-corpus invariant of the reference's Spark
ParagraphVectors / TextPipeline)."""

import numpy as np

WORDS_A = [f"fruit{i}" for i in range(8)]
WORDS_B = [f"metal{i}" for i in range(8)]
N_DOCS = 24
DOC_LEN = 60


def build_docs():
    rng = np.random.default_rng(7)
    docs = []
    for i in range(N_DOCS):
        # parity-interleaved topics: round-robin doc sharding still hands
        # every process a balanced mix of both topics
        topic = WORDS_A if i % 2 == 0 else WORDS_B
        content = " ".join(rng.choice(topic, DOC_LEN))
        docs.append((content, [f"DOC_{i}"]))
    return docs


def doc_topic_separation(label_vecs: np.ndarray) -> float:
    """mean(in-topic doc cosine) - mean(cross-topic doc cosine) where doc
    i's topic is i % 2; strongly positive for any successful run."""
    m = label_vecs / np.maximum(
        np.linalg.norm(label_vecs, axis=1, keepdims=True), 1e-9)
    sim = m @ m.T
    a = np.arange(0, N_DOCS, 2)
    b = np.arange(1, N_DOCS, 2)
    in_a = sim[np.ix_(a, a)][np.triu_indices(len(a), 1)]
    in_b = sim[np.ix_(b, b)][np.triu_indices(len(b), 1)]
    cross = sim[np.ix_(a, b)].ravel()
    return float(np.concatenate([in_a, in_b]).mean() - cross.mean())


def build_pv(docs):
    """The one PV config both the workers and the single-process
    reference use."""
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    return (ParagraphVectors.builder()
            .iterate(docs)
            .layer_size(24)
            .window_size(3)
            .min_word_frequency(1)
            .epochs(10)
            .seed(11)
            .learning_rate(0.05)
            .negative_sample(5)
            .train_words_vectors(True)  # word pairs bootstrap syn1neg,
            .build())                   # which pulls the doc rows
