import os
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, MixtureOfExpertsLayer, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.orbax_serializer import OrbaxModelSerializer
from deeplearning4j_tpu.updaters import Adam


def _net(seed=0, moe=False):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation="relu")))
    if moe:
        b = b.layer(MixtureOfExpertsLayer(n_experts=2, capacity_factor=2.0))
    conf = (b.layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return DataSet(x, y)


class TestOrbaxSerializer:
    """TPU-native checkpoint path (SURVEY §7 'tensorstore path'):
    pytrees saved via Orbax, shardings preserved on restore."""

    def test_round_trip_outputs_and_resume(self, tmp_path):
        net = _net()
        ds = _data()
        net.fit(ds, epochs=3, batch_size=16)
        out = net.output(ds.features)
        d = str(tmp_path / "ckpt")
        OrbaxModelSerializer.save(net, d)

        back = OrbaxModelSerializer.restore(d)
        np.testing.assert_allclose(np.asarray(back.output(ds.features)),
                                   np.asarray(out), atol=1e-6)
        assert back.iteration == net.iteration
        # resume training continues bit-compatibly with the original
        net.fit(ds, epochs=1, batch_size=16)
        back.fit(ds, epochs=1, batch_size=16)
        np.testing.assert_allclose(back.params_flat(), net.params_flat(),
                                   rtol=1e-6)

    def test_sharded_restore_preserves_placement(self, tmp_path):
        from deeplearning4j_tpu.parallel import ExpertParallelWrapper, TrainingMesh

        net = _net(seed=3, moe=True)
        mesh = TrainingMesh(data=4, expert=2)
        wrap = ExpertParallelWrapper(net, mesh).place()
        ds = _data(3)
        for _ in range(2):
            wrap.fit_batch(ds.features, ds.labels)
        d = str(tmp_path / "ep_ckpt")
        # sharded save: no host gather of the expert-sharded params
        OrbaxModelSerializer.save(net, d)

        template = _net(seed=3, moe=True)
        ExpertParallelWrapper(template, mesh).place()
        back = OrbaxModelSerializer.restore(d, template=template)
        # restored onto the SAME expert sharding
        assert back.params_[1]["W1"].sharding.spec[0] == "expert"
        for p_a, p_b in zip(net.params_, back.params_):
            for k in p_a:
                np.testing.assert_allclose(np.asarray(p_a[k]),
                                           np.asarray(p_b[k]), atol=1e-7,
                                           err_msg=k)

    def test_computation_graph_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.resnet50 import ResNet50

        net = ResNet50(num_classes=4, height=32, width=32).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 2)]
        net.fit(DataSet(x, y), batch_size=2)
        d = str(tmp_path / "cg")
        OrbaxModelSerializer.save(net, d)
        back = OrbaxModelSerializer.restore(d)
        np.testing.assert_allclose(
            np.asarray(back.output_single(x)), np.asarray(net.output_single(x)),
            atol=1e-6)

    def test_non_empty_directory_rejected_unless_overwrite(self, tmp_path):
        net = _net()
        d = str(tmp_path / "ckpt")
        OrbaxModelSerializer.save(net, d)
        with pytest.raises(ValueError, match="not empty"):
            OrbaxModelSerializer.save(net, d)
        net.iteration = 42
        OrbaxModelSerializer.save(net, d, overwrite=True)
        assert OrbaxModelSerializer.restore(d).iteration == 42


class TestOrbaxCheckpointListener:
    def test_periodic_orbax_checkpoints_with_retention(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener
        from deeplearning4j_tpu.train.orbax_serializer import (
            OrbaxModelSerializer,
        )

        net = _net()
        lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                 keep_mode="last", keep_last=2,
                                 serializer="orbax")
        net.listeners.append(lst)
        ds = _data()
        net.fit(ds, epochs=5, batch_size=16)  # 5 saves, keep last 2
        assert len(lst.checkpoints) == 2
        dirs = [d for d in os.listdir(tmp_path)
                if os.path.isdir(tmp_path / d)]
        assert len(dirs) == 2
        back = OrbaxModelSerializer.restore(lst.checkpoints[-1])
        np.testing.assert_allclose(np.asarray(back.output(ds.features)),
                                   np.asarray(net.output(ds.features)),
                                   atol=1e-6)

    def test_bad_serializer_rejected(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        with pytest.raises(ValueError, match="serializer"):
            CheckpointListener(str(tmp_path), serializer="msgpack")

    def test_last_and_every_retention_indexes_by_checkpoint_number(self, tmp_path):
        """keep_every must track checkpoint NUMBERS: every-2nd checkpoints
        stay kept even after earlier ones are deleted."""
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        net = _net()
        lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                 keep_mode="last_and_every", keep_last=1,
                                 keep_every=2)
        net.listeners.append(lst)
        ds = _data()
        net.fit(ds, epochs=5, batch_size=16)  # checkpoints 1..5
        kept = sorted(os.path.basename(p) for p in lst.checkpoints)
        # every-2nd (2, 4) + last (5)
        assert any("checkpoint_2_" in p for p in kept), kept
        assert any("checkpoint_4_" in p for p in kept), kept
        assert any("checkpoint_5_" in p for p in kept), kept
        assert len(kept) == 3

    def test_orbax_listener_restart_overwrites(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        ds = _data()
        for _ in range(2):  # second "run" re-saves the same step names
            net = _net()
            lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                     serializer="orbax")
            net.listeners.append(lst)
            net.fit(ds, epochs=1, batch_size=16)
        assert os.path.isdir(tmp_path / "checkpoint_1_iter_1_epoch_0")

    def test_orbax_wall_clock_trigger_rejected(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        with pytest.raises(ValueError, match="wall clock"):
            CheckpointListener(str(tmp_path), save_every_minutes=1,
                               serializer="orbax")

    def test_listener_counter_resumes_past_existing_checkpoints(self, tmp_path):
        """A restarted run must continue numbering after the previous
        run's checkpoints, not collide with them."""
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        ds = _data()
        net = _net()
        l1 = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                serializer="orbax")
        net.listeners.append(l1)
        net.fit(ds, epochs=2, batch_size=16)  # checkpoints 1, 2

        net2 = _net()
        l2 = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                serializer="orbax")
        assert l2._counter == 2  # resumed numbering
        net2.listeners.append(l2)
        net2.fit(ds, epochs=1, batch_size=16)
        names = sorted(f for f in os.listdir(tmp_path))
        assert any(f.startswith("checkpoint_3_") for f in names), names
        # prior run's checkpoints untouched
        assert any(f.startswith("checkpoint_1_") for f in names)

    def test_restore_fills_state_keys_added_after_save(self, tmp_path):
        """Forward compat: a checkpoint saved before a layer grew a state
        key must still restore, with the new key from the fresh init."""
        import shutil

        from deeplearning4j_tpu.train.orbax_serializer import (
            OrbaxModelSerializer, _checkpointer,
        )

        net = _net(moe=True)
        ds = _data()
        net.fit(ds, epochs=2, batch_size=16)
        d = str(tmp_path / "old_ckpt")
        OrbaxModelSerializer.save(net, d)
        # simulate an old checkpoint: rewrite layer_state WITHOUT the
        # expert_load key
        old_state = [dict(s) for s in net.state_]
        del old_state[1]["expert_load"]
        shutil.rmtree(os.path.join(d, "layer_state"))
        ck = _checkpointer()
        ck.save(os.path.join(d, "layer_state"), old_state)
        ck.close()

        back = OrbaxModelSerializer.restore(d)
        # saved keys restored, missing key filled from init
        np.testing.assert_allclose(
            np.asarray(back.state_[1]["aux_loss"]),
            np.asarray(net.state_[1]["aux_loss"]))
        assert back.state_[1]["expert_load"].shape == (2,)
        out = back.output(ds.features)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(net.output(ds.features)),
                                   atol=1e-6)

    def test_zip_restore_with_changed_state_layout_keeps_fresh_state(self, tmp_path):
        """Old zip checkpoints whose layer-state vector no longer matches
        the current layout must restore (params intact) with a warning,
        not crash."""
        import warnings as _warnings
        import zipfile

        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = _net(moe=True)
        ds = _data()
        net.fit(ds, epochs=1, batch_size=16)
        p = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, p)
        # simulate an old checkpoint: truncate the state entry to one fp32
        with zipfile.ZipFile(p) as z:
            entries = {n: z.read(n) for n in z.namelist()}
        entries["state.bin"] = np.zeros(1, "<f4").tobytes()
        with zipfile.ZipFile(p, "w") as z:
            for n, b in entries.items():
                z.writestr(n, b)
        with _warnings.catch_warnings(record=True) as w:
            _warnings.simplefilter("always")
            back = ModelSerializer.restore_multi_layer_network(p)
        assert any("layer-state size" in str(x.message) for x in w)
        np.testing.assert_allclose(back.params_flat(), net.params_flat())
