"""Fused Pallas conv+BN+ReLU kernels (VERDICT r3 item 1): interpreter-
mode value/gradient parity against the XLA reference composition,
FusedResNetBottleneck block semantics, the compile-probe gate, and the
ResNet-50 wiring. Mirrors the reference's cuDNN-vs-builtin validation
pattern (``CuDNNGradientChecks.java``): the fast path must agree with
the canonical path on values AND gradients before it may serve."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.ops import fused_conv as fc

RNG = np.random.default_rng(7)


def _mk_pw(m=200, cin=96, cout=160):
    x = jnp.asarray(RNG.standard_normal((m, cin)), jnp.bfloat16)
    s = jnp.asarray(RNG.standard_normal(cin) * 0.2 + 1.0, jnp.float32)
    t = jnp.asarray(RNG.standard_normal(cin) * 0.1, jnp.float32)
    w = jnp.asarray(RNG.standard_normal((cin, cout)) * 0.05, jnp.bfloat16)
    return x, s, t, w


def _mk_c3(n=3, h=10, wd=12, cin=40, cout=72):
    x = jnp.asarray(RNG.standard_normal((n, h, wd, cin)), jnp.bfloat16)
    s = jnp.asarray(RNG.standard_normal(cin) * 0.2 + 1.0, jnp.float32)
    t = jnp.asarray(RNG.standard_normal(cin) * 0.1, jnp.float32)
    w = jnp.asarray(RNG.standard_normal((3, 3, cin, cout)) * 0.05,
                    jnp.bfloat16)
    return x, s, t, w


def _loss(fn, mixed_cotangents=True):
    """Scalar touching y AND stats so both cotangent paths are exercised."""
    def f(args):
        y, st = fn(*args)
        out = jnp.sum(y.astype(jnp.float32) * 0.01)
        if mixed_cotangents:
            out = out + jnp.sum(st * jnp.asarray([[0.002], [0.0005]]))
        return out.astype(jnp.float32)
    return f


class TestKernelParity:
    """Pallas (interpreter) vs XLA reference — fwd values, statistics,
    and all four gradients, on deliberately tile-unaligned shapes."""

    @pytest.mark.parametrize("relu_in", [False, True])
    def test_pointwise_forward(self, relu_in):
        args = _mk_pw()
        y1, st1 = fc.pw_conv(*args, relu_in, True)
        y2, st2 = fc.pw_conv_reference(*args, relu_in)
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=1e-5)
        np.testing.assert_allclose(np.asarray(st1), np.asarray(st2),
                                   rtol=1e-4, atol=1e-3)

    @pytest.mark.parametrize("relu_in", [False, True])
    def test_conv3x3_forward(self, relu_in):
        args = _mk_c3()
        y1, st1 = fc.conv3x3(*args, relu_in, True)
        y2, st2 = fc.conv3x3_reference(*args, relu_in)
        # 9-matmul accumulation order vs XLA's conv: one bf16 ulp
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=2e-3)
        np.testing.assert_allclose(np.asarray(st1), np.asarray(st2),
                                   rtol=1e-4, atol=1e-3)

    @pytest.mark.parametrize("op,mk", [
        ("pw", _mk_pw), ("c3", _mk_c3)], ids=["pointwise", "conv3x3"])
    def test_gradients_match_reference(self, op, mk):
        args = mk()
        kern = functools.partial(
            fc.pw_conv if op == "pw" else fc.conv3x3,
            relu_in=True, interpret=True)
        ref = functools.partial(
            fc.pw_conv_reference if op == "pw" else fc.conv3x3_reference,
            relu_in=True)
        gk = jax.grad(_loss(kern))(args)
        gr = jax.grad(_loss(ref))(args)
        for name, a, b in zip(("dx", "dscale", "dshift", "dW"), gk, gr):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            # bf16 cotangent casts inside the kernel → bf16-ulp noise
            np.testing.assert_allclose(
                a, b, atol=0.03, rtol=0.05,
                err_msg=f"{op} gradient {name} diverged")

    def test_stats_cotangent_reaches_producer(self):
        """The downstream BN's gradient enters through the stats output —
        zeroing it must CHANGE dW (i.e. stats are a live VJP path)."""
        args = _mk_pw(m=64, cin=128, cout=128)
        kern = functools.partial(fc.pw_conv, relu_in=False, interpret=True)
        g_with = jax.grad(_loss(kern, mixed_cotangents=True))(args)[3]
        g_without = jax.grad(_loss(kern, mixed_cotangents=False))(args)[3]
        assert np.abs(np.asarray(g_with, np.float32)
                      - np.asarray(g_without, np.float32)).max() > 1e-4


class TestProbeGate:
    def test_probe_rejects_on_non_tpu_backend(self):
        """On the CPU test backend the Mosaic lowering must fail the
        probe → False, and the layer silently uses the XLA path (the
        flash-kernel gating contract)."""
        fc._PROBE_CACHE.clear()
        try:
            assert fc.fused_conv_available(jnp.bfloat16) is False
        finally:
            fc._PROBE_CACHE.clear()


class TestFusedBottleneckBlock:
    def _layer(self, cin=32, width=8, stride=1, project=False):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=width, stride=stride,
                                    project=project)
        it = InputType.convolutional(8, 8, cin)
        lay.initialize(it)
        params = lay.init_params(jax.random.PRNGKey(0), it)
        state = lay.init_layer_state(it)
        return lay, params, state

    def test_forward_shapes_and_state_update(self):
        lay, params, state = self._layer(cin=32, width=8)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 32)), jnp.float32)
        y, ns = lay.apply(params, x, state=state, train=True)
        assert y.shape == (2, 8, 8, 32)
        assert float(jnp.min(y)) >= 0.0  # post-residual relu
        # running stats moved off their init values
        assert np.abs(np.asarray(ns["mean_c"])).max() > 0
        # eval mode uses (different) running stats → different output
        y_eval, ns2 = lay.apply(params, x, state=ns, train=False)
        assert not np.allclose(np.asarray(y), np.asarray(y_eval))
        for k in ns2:  # eval does not update running stats
            np.testing.assert_array_equal(np.asarray(ns2[k]),
                                          np.asarray(ns[k]))

    def test_stride2_projection_geometry(self):
        lay, params, state = self._layer(cin=32, width=8, stride=2,
                                         project=True)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 32)), jnp.float32)
        y, _ = lay.apply(params, x, state=state, train=True)
        assert y.shape == (2, 4, 4, 32)

    def test_identity_shortcut_channel_check(self):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=8, project=False)
        with pytest.raises(ValueError, match="identity shortcut"):
            lay.initialize(InputType.convolutional(8, 8, 48))

    def test_block_matches_unfused_composition(self):
        """The fused block's train-mode forward equals the equivalent
        conv→BN→relu XLA composition with copied weights (fp32)."""
        lay, params, state = self._layer(cin=16, width=4, project=True)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 16)), jnp.float32)
        y, _ = lay.apply(params, x, state=state, train=True)

        def bn_relu(z, gamma, beta, relu=True):
            mean = z.mean((0, 1, 2))
            var = jnp.maximum((z * z).mean((0, 1, 2)) - mean * mean, 0.0)
            out = (z - mean) * jax.lax.rsqrt(var + lay.eps) * gamma + beta
            return jnp.maximum(out, 0) if relu else out

        za = jnp.einsum("nhwc,cd->nhwd", x, params["W_a"])
        a = bn_relu(za, params["gamma_a"], params["beta_a"])
        zb = jax.lax.conv_general_dilated(
            a, params["W_b"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        b = bn_relu(zb, params["gamma_b"], params["beta_b"])
        zc = jnp.einsum("nhwc,cd->nhwd", b, params["W_c"])
        c = bn_relu(zc, params["gamma_c"], params["beta_c"], relu=False)
        zp = jnp.einsum("nhwc,cd->nhwd", x, params["W_p"])
        p = bn_relu(zp, params["gamma_p"], params["beta_p"], relu=False)
        want = jnp.maximum(c + p, 0)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)

    def test_pallas_path_matches_reference_path(self, monkeypatch):
        """Force the Pallas kernels (interpreter) through the block and
        compare against the XLA-reference path — the full block-level
        fwd+bwd agreement the cuDNN checks pattern requires."""
        from deeplearning4j_tpu.nn.conf.layers import fused_block as fb

        lay, params, state = self._layer(cin=16, width=4, project=True)
        x32 = RNG.standard_normal((2, 8, 8, 16))
        x = jnp.asarray(x32, jnp.bfloat16)
        bf_params = {k: (v.astype(jnp.bfloat16) if k.startswith("W_") else v)
                     for k, v in params.items()}

        def run():
            def loss(p):
                y, _ = lay.apply(p, x, state=state, train=True)
                return jnp.sum(y.astype(jnp.float32) ** 2).astype(jnp.float32)
            val, grads = jax.value_and_grad(loss)(bf_params)
            return val, grads

        monkeypatch.setattr(lay, "_pallas_enabled", lambda x: False)
        v_ref, g_ref = run()
        # route the block through interpreter-mode pallas
        monkeypatch.setattr(lay, "_pallas_enabled", lambda x: True)
        pw0, c30 = fc.pw_conv, fc.conv3x3
        monkeypatch.setattr(
            fc, "pw_conv", lambda x_, s, t, w, r, i: pw0(x_, s, t, w, r, True))
        monkeypatch.setattr(
            fc, "conv3x3", lambda x_, s, t, w, r, i: c30(x_, s, t, w, r, True))
        v_pal, g_pal = run()
        assert abs(float(v_pal) - float(v_ref)) < 0.05 * (abs(float(v_ref))
                                                          + 1.0)
        for k in g_ref:
            a = np.asarray(g_ref[k], np.float32)
            b = np.asarray(g_pal[k], np.float32)
            np.testing.assert_allclose(
                b, a, atol=0.05 * (np.abs(a).max() + 1e-3) + 1e-3,
                err_msg=f"block gradient {k} diverged")


class TestFusedBlockPersistence:
    def test_serde_round_trip(self):
        """FusedResNetBottleneck survives the JSON config round trip
        (the new layer must join the serialization-regression contract)."""
        from deeplearning4j_tpu.nn.conf import serde
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=8, stride=2, project=True,
                                    decay=0.95, eps=2e-5)
        back = serde.decode(serde.encode(lay))
        assert isinstance(back, FusedResNetBottleneck)
        assert (back.width, back.stride, back.project) == (8, 2, True)
        assert (back.decay, back.eps) == (0.95, 2e-5)

    def test_checkpoint_round_trip_fused_model(self, tmp_path):
        """A fused ResNet saves/restores through ModelSerializer with
        bit-equal outputs (zip layout flattens the block's param dict)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.models.resnet50 import ResNet50
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = ResNet50(num_classes=3, height=64, width=64,
                       fused_pallas=True).init()
        x = RNG.standard_normal((2, 64, 64, 3)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, 2)]
        net.fit(DataSet(x, y), epochs=1)
        path = str(tmp_path / "fused.zip")
        ModelSerializer.write_model(net, path)
        net2 = ModelSerializer.restore_computation_graph(path)
        np.testing.assert_allclose(np.asarray(net.output_single(x)),
                                   np.asarray(net2.output_single(x)),
                                   atol=1e-6)

    def test_mixed_precision_keeps_bn_affines_fp32(self):
        """Under compute_dtype=bfloat16 the conv weights cast to bf16 but
        the keep_fp32_params BN affines stay fp32 inside the compute
        cast (matching the standalone BatchNormalization exclusion)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck
        from deeplearning4j_tpu.nn.multilayer import (
            _cast_layer_params_for_compute,
        )

        lay = FusedResNetBottleneck(width=4, project=True)
        it = InputType.convolutional(8, 8, 16)
        lay.initialize(it)
        params = lay.init_params(jax.random.PRNGKey(0), it)
        cast = _cast_layer_params_for_compute(lay, params, jnp.bfloat16,
                                              is_output=False)
        assert cast["W_a"].dtype == jnp.bfloat16
        assert cast["W_b"].dtype == jnp.bfloat16
        assert cast["gamma_a"].dtype == jnp.float32
        assert cast["beta_c"].dtype == jnp.float32


class TestResNet50Wiring:
    @pytest.mark.slow
    def test_fused_resnet50_small_trains(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.models.resnet50 import ResNet50

        net = ResNet50(num_classes=5, height=64, width=64,
                       fused_pallas=True).init()
        x = RNG.standard_normal((2, 64, 64, 3)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[RNG.integers(0, 5, 2)]
        net.fit(DataSet(x, y), epochs=1)
        out = net.output_single(x)
        assert out.shape == (2, 5)
        assert np.all(np.isfinite(np.asarray(out, np.float32)))

    def test_fused_conf_has_one_vertex_per_block(self):
        from deeplearning4j_tpu.models.resnet50 import ResNet50

        conf = ResNet50(num_classes=10, fused_pallas=True).conf()
        names = list(conf.vertices)
        assert "s0b0" in names and "s3b2" in names
        assert not any(n.endswith("_a_conv") for n in names)
