"""Fused Pallas conv+BN+ReLU kernels (VERDICT r3 item 1): interpreter-
mode value/gradient parity against the XLA reference composition,
FusedResNetBottleneck block semantics, the compile-probe gate, and the
ResNet-50 wiring. Mirrors the reference's cuDNN-vs-builtin validation
pattern (``CuDNNGradientChecks.java``): the fast path must agree with
the canonical path on values AND gradients before it may serve."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.ops import fused_conv as fc

RNG = np.random.default_rng(7)


class TestKernelParityIsolated:
    """Pallas (interpreter) vs XLA reference — fwd values, statistics,
    all four gradients, the stats-cotangent liveness check, and the
    block-level pallas-vs-reference parity, on tile-unaligned shapes.

    Runs in a SUBPROCESS (tests/fused_interp_worker.py): interpret-mode
    pallas_call on the multi-device CPU backend can leave the runtime in
    a state where a LATER unrelated shard_map program raw-SIGABRTs
    (bisected r4: any interpreted kernel here followed by the EP+SP MoE
    step crashed the suite; isolation kills the corruption with the
    process while keeping identical coverage)."""

    def test_interpreter_parity_suite(self):
        import subprocess
        import sys

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "fused_interp_worker.py")],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode == 0, (
            f"worker failed\nstdout:\n{proc.stdout[-3000:]}\n"
            f"stderr:\n{proc.stderr[-3000:]}")
        assert "ALL-OK" in proc.stdout


class TestProbeGate:
    def test_probe_rejects_on_non_tpu_backend(self):
        """On the CPU test backend the Mosaic lowering must fail the
        probe → False, and the layer silently uses the XLA path (the
        flash-kernel gating contract)."""
        fc._PROBE_CACHE.clear()
        try:
            assert fc.fused_conv_available(jnp.bfloat16) is False
        finally:
            fc._PROBE_CACHE.clear()


class TestFusedBottleneckBlock:
    def _layer(self, cin=32, width=8, stride=1, project=False):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=width, stride=stride,
                                    project=project)
        it = InputType.convolutional(8, 8, cin)
        lay.initialize(it)
        params = lay.init_params(jax.random.PRNGKey(0), it)
        state = lay.init_layer_state(it)
        return lay, params, state

    def test_forward_shapes_and_state_update(self):
        lay, params, state = self._layer(cin=32, width=8)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 32)), jnp.float32)
        y, ns = lay.apply(params, x, state=state, train=True)
        assert y.shape == (2, 8, 8, 32)
        assert float(jnp.min(y)) >= 0.0  # post-residual relu
        # running stats moved off their init values
        assert np.abs(np.asarray(ns["mean_c"])).max() > 0
        # eval mode uses (different) running stats → different output
        y_eval, ns2 = lay.apply(params, x, state=ns, train=False)
        assert not np.allclose(np.asarray(y), np.asarray(y_eval))
        for k in ns2:  # eval does not update running stats
            np.testing.assert_array_equal(np.asarray(ns2[k]),
                                          np.asarray(ns[k]))

    def test_stride2_projection_geometry(self):
        lay, params, state = self._layer(cin=32, width=8, stride=2,
                                         project=True)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 32)), jnp.float32)
        y, _ = lay.apply(params, x, state=state, train=True)
        assert y.shape == (2, 4, 4, 32)

    def test_identity_shortcut_channel_check(self):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=8, project=False)
        with pytest.raises(ValueError, match="identity shortcut"):
            lay.initialize(InputType.convolutional(8, 8, 48))

    def test_block_matches_unfused_composition(self):
        """The fused block's train-mode forward equals the equivalent
        conv→BN→relu XLA composition with copied weights (fp32)."""
        lay, params, state = self._layer(cin=16, width=4, project=True)
        x = jnp.asarray(RNG.standard_normal((2, 8, 8, 16)), jnp.float32)
        y, _ = lay.apply(params, x, state=state, train=True)

        def bn_relu(z, gamma, beta, relu=True):
            mean = z.mean((0, 1, 2))
            var = jnp.maximum((z * z).mean((0, 1, 2)) - mean * mean, 0.0)
            out = (z - mean) * jax.lax.rsqrt(var + lay.eps) * gamma + beta
            return jnp.maximum(out, 0) if relu else out

        za = jnp.einsum("nhwc,cd->nhwd", x, params["W_a"])
        a = bn_relu(za, params["gamma_a"], params["beta_a"])
        zb = jax.lax.conv_general_dilated(
            a, params["W_b"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        b = bn_relu(zb, params["gamma_b"], params["beta_b"])
        zc = jnp.einsum("nhwc,cd->nhwd", b, params["W_c"])
        c = bn_relu(zc, params["gamma_c"], params["beta_c"], relu=False)
        zp = jnp.einsum("nhwc,cd->nhwd", x, params["W_p"])
        p = bn_relu(zp, params["gamma_p"], params["beta_p"], relu=False)
        want = jnp.maximum(c + p, 0)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)


class TestFusedBlockPersistence:
    def test_serde_round_trip(self):
        """FusedResNetBottleneck survives the JSON config round trip
        (the new layer must join the serialization-regression contract)."""
        from deeplearning4j_tpu.nn.conf import serde
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

        lay = FusedResNetBottleneck(width=8, stride=2, project=True,
                                    decay=0.95, eps=2e-5)
        back = serde.decode(serde.encode(lay))
        assert isinstance(back, FusedResNetBottleneck)
        assert (back.width, back.stride, back.project) == (8, 2, True)
        assert (back.decay, back.eps) == (0.95, 2e-5)

    def test_checkpoint_round_trip_fused_model(self, tmp_path):
        """A fused ResNet saves/restores through ModelSerializer with
        bit-equal outputs (zip layout flattens the block's param dict)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.models.resnet50 import ResNet50
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        net = ResNet50(num_classes=3, height=64, width=64,
                       fused_pallas=True).init()
        x = RNG.standard_normal((2, 64, 64, 3)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, 2)]
        net.fit(DataSet(x, y), epochs=1)
        path = str(tmp_path / "fused.zip")
        ModelSerializer.write_model(net, path)
        net2 = ModelSerializer.restore_computation_graph(path)
        np.testing.assert_allclose(np.asarray(net.output_single(x)),
                                   np.asarray(net2.output_single(x)),
                                   atol=1e-6)

    def test_mixed_precision_keeps_bn_affines_fp32(self):
        """Under compute_dtype=bfloat16 the conv weights cast to bf16 but
        the keep_fp32_params BN affines stay fp32 inside the compute
        cast (matching the standalone BatchNormalization exclusion)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck
        from deeplearning4j_tpu.nn.multilayer import (
            _cast_layer_params_for_compute,
        )

        lay = FusedResNetBottleneck(width=4, project=True)
        it = InputType.convolutional(8, 8, 16)
        lay.initialize(it)
        params = lay.init_params(jax.random.PRNGKey(0), it)
        cast = _cast_layer_params_for_compute(lay, params, jnp.bfloat16,
                                              is_output=False)
        assert cast["W_a"].dtype == jnp.bfloat16
        assert cast["W_b"].dtype == jnp.bfloat16
        assert cast["gamma_a"].dtype == jnp.float32
        assert cast["beta_c"].dtype == jnp.float32


class TestResNet50Wiring:
    @pytest.mark.slow
    def test_fused_resnet50_small_trains(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.models.resnet50 import ResNet50

        net = ResNet50(num_classes=5, height=64, width=64,
                       fused_pallas=True).init()
        x = RNG.standard_normal((2, 64, 64, 3)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[RNG.integers(0, 5, 2)]
        net.fit(DataSet(x, y), epochs=1)
        out = net.output_single(x)
        assert out.shape == (2, 5)
        assert np.all(np.isfinite(np.asarray(out, np.float32)))

    def test_fused_conf_has_one_vertex_per_block(self):
        from deeplearning4j_tpu.models.resnet50 import ResNet50

        conf = ResNet50(num_classes=10, fused_pallas=True).conf()
        names = list(conf.vertices)
        assert "s0b0" in names and "s3b2" in names
        assert not any(n.endswith("_a_conv") for n in names)
