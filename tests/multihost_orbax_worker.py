"""Worker for the multi-host Orbax checkpoint test — 2 processes × 2 CPU
devices save ONE cooperative TensorStore checkpoint from a global-mesh
model, then restore it into a placed template and verify parameter
equality (the jax.distributed checkpoint story OrbaxModelSerializer
claims).

Usage: python multihost_orbax_worker.py <coordinator> <num_procs> <pid> <outdir>
"""

import os
import sys

coordinator, nprocs, pid, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel.multihost import (  # noqa: E402
    MultiHostNetwork,
    ParameterAveragingTrainingMaster,
    ShardedDataSetIterator,
    initialize,
)
from deeplearning4j_tpu.train.orbax_serializer import (  # noqa: E402
    OrbaxModelSerializer,
)
from tests.multihost_model import build_net, global_batches  # noqa: E402

ctx = initialize(coordinator, num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs

net = build_net()
facade = MultiHostNetwork(
    net, ParameterAveragingTrainingMaster.Builder().build(), ctx)
facade.fit(ShardedDataSetIterator(global_batches(), nprocs, pid), epochs=1)
trained = np.asarray(net.params_flat())

ckpt_dir = os.path.join(outdir, "orbax_mh")
OrbaxModelSerializer.save(net, ckpt_dir)  # cooperative across processes

# metadata must come from process 0 only — but exist for everyone
assert os.path.exists(os.path.join(ckpt_dir, "meta.json"))

# restore into a placed template: a fresh net trained LONGER (2 epochs)
# so its params provably differ from the checkpoint before restore —
# a no-op restore cannot pass the equality check below
net2 = build_net()
facade2 = MultiHostNetwork(
    net2, ParameterAveragingTrainingMaster.Builder().build(), ctx)
facade2.fit(ShardedDataSetIterator(global_batches(), nprocs, pid), epochs=2)
pre_restore = np.asarray(net2.params_flat())
assert not np.allclose(pre_restore, trained), "template must differ"
restored = OrbaxModelSerializer.restore(ckpt_dir, template=net2)
np.testing.assert_allclose(
    np.asarray(restored.params_flat()), trained, rtol=1e-6, atol=1e-7)

with open(os.path.join(outdir, f"orbax_ok_{pid}"), "w") as f:
    f.write("ok")
print(f"worker {pid}: orbax multi-host save/restore OK")
