"""Interpreter-mode fused-kernel parity checks, run in their OWN process
by tests/test_fused_conv.py.

Why a subprocess: interpret-mode ``pallas_call`` on the multi-device CPU
backend leaves the runtime in a state where a LATER unrelated shard_map
program can abort (raw SIGABRT in device-to-host transfer; bisected in
round 4 — eager or jitted makes no difference, and the same crash never
happens when the interpreted kernels ran in a different process). The
parity coverage is identical; the corruption dies with this process.

Exit 0 = every check passed.
"""

import functools
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.ops import fused_conv as fc

RNG = np.random.default_rng(7)


def _mk_pw(m=200, cin=96, cout=160):
    x = jnp.asarray(RNG.standard_normal((m, cin)), jnp.bfloat16)
    s = jnp.asarray(RNG.standard_normal(cin) * 0.2 + 1.0, jnp.float32)
    t = jnp.asarray(RNG.standard_normal(cin) * 0.1, jnp.float32)
    w = jnp.asarray(RNG.standard_normal((cin, cout)) * 0.05, jnp.bfloat16)
    return x, s, t, w


def _mk_c3(n=3, h=10, wd=12, cin=40, cout=72):
    x = jnp.asarray(RNG.standard_normal((n, h, wd, cin)), jnp.bfloat16)
    s = jnp.asarray(RNG.standard_normal(cin) * 0.2 + 1.0, jnp.float32)
    t = jnp.asarray(RNG.standard_normal(cin) * 0.1, jnp.float32)
    w = jnp.asarray(RNG.standard_normal((3, 3, cin, cout)) * 0.05,
                    jnp.bfloat16)
    return x, s, t, w


def _loss(fn, mixed_cotangents=True):
    def f(args):
        y, st = fn(*args)
        out = jnp.sum(y.astype(jnp.float32) * 0.01)
        if mixed_cotangents:
            out = out + jnp.sum(st * jnp.asarray([[0.002], [0.0005]]))
        return out.astype(jnp.float32)
    return f


def check_pointwise_forward():
    for relu_in in (False, True):
        args = _mk_pw()
        y1, st1 = fc.pw_conv(*args, relu_in, True)
        y2, st2 = fc.pw_conv_reference(*args, relu_in)
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=1e-5)
        np.testing.assert_allclose(np.asarray(st1), np.asarray(st2),
                                   rtol=1e-4, atol=1e-3)


def check_conv3x3_forward():
    for relu_in in (False, True):
        args = _mk_c3()
        y1, st1 = fc.conv3x3(*args, relu_in, True)
        y2, st2 = fc.conv3x3_reference(*args, relu_in)
        # 9-matmul accumulation order vs XLA's conv: one bf16 ulp
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=2e-3)
        np.testing.assert_allclose(np.asarray(st1), np.asarray(st2),
                                   rtol=1e-4, atol=1e-3)


def check_gradients():
    for op, mk in (("pw", _mk_pw), ("c3", _mk_c3)):
        args = mk()
        kern = functools.partial(
            fc.pw_conv if op == "pw" else fc.conv3x3,
            relu_in=True, interpret=True)
        ref = functools.partial(
            fc.pw_conv_reference if op == "pw" else fc.conv3x3_reference,
            relu_in=True)
        gk = jax.grad(_loss(kern))(args)
        gr = jax.grad(_loss(ref))(args)
        for name, a, b in zip(("dx", "dscale", "dshift", "dW"), gk, gr):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            # bf16 cotangent casts inside the kernel → bf16-ulp noise
            np.testing.assert_allclose(
                a, b, atol=0.03, rtol=0.05,
                err_msg=f"{op} gradient {name} diverged")


def check_stats_cotangent_is_live():
    args = _mk_pw(m=64, cin=128, cout=128)
    kern = functools.partial(fc.pw_conv, relu_in=False, interpret=True)
    g_with = jax.grad(_loss(kern, mixed_cotangents=True))(args)[3]
    g_without = jax.grad(_loss(kern, mixed_cotangents=False))(args)[3]
    assert np.abs(np.asarray(g_with, np.float32)
                  - np.asarray(g_without, np.float32)).max() > 1e-4


def check_block_pallas_path_matches_reference():
    """Full block (FusedResNetBottleneck) through interpreter-mode Pallas
    vs the XLA-reference path — values and gradients."""
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.layers import FusedResNetBottleneck

    lay = FusedResNetBottleneck(width=4, project=True)
    it = InputType.convolutional(8, 8, 16)
    lay.initialize(it)
    params = lay.init_params(jax.random.PRNGKey(0), it)
    state = lay.init_layer_state(it)
    x = jnp.asarray(RNG.standard_normal((2, 8, 8, 16)), jnp.bfloat16)
    bf_params = {k: (v.astype(jnp.bfloat16) if k.startswith("W_") else v)
                 for k, v in params.items()}

    def run():
        def loss(p):
            y, _ = lay.apply(p, x, state=state, train=True)
            return jnp.sum(y.astype(jnp.float32) ** 2).astype(jnp.float32)
        return jax.value_and_grad(loss)(bf_params)

    lay._pallas_enabled = lambda x: False
    v_ref, g_ref = run()
    # route through interpreter-mode pallas
    lay._pallas_enabled = lambda x: True
    pw0, c30 = fc.pw_conv, fc.conv3x3
    fc.pw_conv = lambda x_, s, t, w, r, i: pw0(x_, s, t, w, r, True)
    fc.conv3x3 = lambda x_, s, t, w, r, i: c30(x_, s, t, w, r, True)
    try:
        v_pal, g_pal = run()
    finally:
        fc.pw_conv, fc.conv3x3 = pw0, c30
    assert abs(float(v_pal) - float(v_ref)) < 0.05 * (abs(float(v_ref)) + 1.0)
    for k in g_ref:
        a = np.asarray(g_ref[k], np.float32)
        b = np.asarray(g_pal[k], np.float32)
        np.testing.assert_allclose(
            b, a, atol=0.05 * (np.abs(a).max() + 1e-3) + 1e-3,
            err_msg=f"block gradient {k} diverged")


if __name__ == "__main__":
    check_pointwise_forward()
    print("pointwise forward parity ok", flush=True)
    check_conv3x3_forward()
    print("conv3x3 forward parity ok", flush=True)
    check_gradients()
    print("gradient parity ok", flush=True)
    check_stats_cotangent_is_live()
    print("stats cotangent live ok", flush=True)
    check_block_pallas_path_matches_reference()
    print("block pallas-path parity ok", flush=True)
    print("ALL-OK", flush=True)
