"""Unified chaos-engineering subsystem (deeplearning4j_tpu/chaos/).

Three layers under test (ISSUE 13):

1. the seam machinery itself — hook fire points, the injectable FS
   layer's typed StorageError + cleanup contract, declarative seeded
   ChaosPlans, the invariant checkers;
2. the disk-full hardening satellites — a failed atomic write in the
   checkpoint / registry-journal / tune-store paths raises typed,
   cleans its staging file, and leaves the previous artifact loadable,
   with in-memory state never diverging from disk;
3. the drill matrix — every fast (single-fault) drill runs green in
   tier-1; the paired-fault storms run in the slow tier. A drill going
   red here means an injected fault surfaced as a hang, a bare
   exception, or a corrupt artifact somewhere in the stack.

Plus the PR 11 residue regression (generation traffic feeds the canary
gate) and the install_signal_dump SIGTERM drill (satellite: signal
mid-fit produces an ordered dump AND chains to the previous handler).
"""

import json
import os
import signal
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.chaos import (
    ChaosPlan,
    InvariantReport,
    StorageError,
    hooks,
    load_plan,
)
from deeplearning4j_tpu.chaos import fslayer, invariants
from deeplearning4j_tpu.chaos import drills as chaos_drills
from deeplearning4j_tpu.chaos.hooks import FaultSpec, InjectedFaultError
from deeplearning4j_tpu.obs import flight, lockwitness

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """Nothing armed leaks between tests, and the process-global flight
    recorder's dump_dir mutations are restored."""
    rec = flight.default_flight_recorder()
    prev_dir = rec.dump_dir
    hooks.reset()
    yield
    hooks.reset()
    rec.dump_dir = prev_dir


def _events_since(seq0, kinds=None):
    evs = [e for e in flight.default_flight_recorder().events()
           if e["seq"] >= seq0]
    if kinds is not None:
        evs = [e for e in evs if e["kind"] in kinds]
    return evs


# ===========================================================================
# hooks
# ===========================================================================
class TestHooks:
    def test_unarmed_fire_is_noop(self):
        assert hooks.fire("fs.replace", surface="x") is None

    def test_at_call_match_and_times(self):
        spec = FaultSpec("p", mode="error", at_call=2,
                         match={"surface": "a"})
        with hooks.armed(spec):
            hooks.fire("p", surface="b")      # no match: not counted
            hooks.fire("p", surface="a")      # call 1
            with pytest.raises(InjectedFaultError):
                hooks.fire("p", surface="a")  # call 2 fires
            hooks.fire("p", surface="a")      # times=1 budget spent
        assert spec.calls == 3 and spec.fires == 1
        assert hooks.fire("p", surface="a") is None  # disarmed

    def test_path_substr_match(self):
        spec = FaultSpec("p", mode="error",
                         match={"path_substr": "journal"})
        with hooks.armed(spec):
            hooks.fire("p", path="/tmp/other.json")
            with pytest.raises(InjectedFaultError):
                hooks.fire("p", path="/reg/journal.jsonl")

    def test_prob_is_seeded_deterministic(self):
        import random

        def fires(seed):
            spec = FaultSpec("p", mode="error", prob=0.5, times=None,
                             rng=random.Random(seed))
            out = []
            with hooks.armed(spec):
                for _ in range(20):
                    try:
                        hooks.fire("p")
                        out.append(0)
                    except InjectedFaultError:
                        out.append(1)
            return out

        assert fires(7) == fires(7)
        assert fires(7) != fires(8)

    def test_two_specs_on_one_point_count_independently(self):
        """at_call counting must not drift when an earlier spec on the
        same point fires: spec B's Nth call is the seam's Nth matching
        call, regardless of spec A's injections."""
        a = FaultSpec("p", mode="delay", delay_s=0.0, at_call=2)
        b = FaultSpec("p", mode="error", at_call=4)
        with hooks.armed([a, b]):
            fired_at = None
            for call in range(1, 7):
                try:
                    hooks.fire("p")
                except InjectedFaultError:
                    fired_at = call
        assert a.fires == 1 and a.calls == 6
        assert fired_at == 4 and b.calls == 6

    def test_errno_modes_and_unknown_mode(self):
        with hooks.armed(FaultSpec("p", mode="enospc")):
            with pytest.raises(OSError) as ei:
                hooks.fire("p")
            assert ei.value.errno == 28
        with pytest.raises(ValueError, match="unknown fault mode"):
            FaultSpec("p", mode="nonsense")

    def test_fire_log_and_flight_event(self):
        seq0 = flight.default_flight_recorder().recorded_total
        hooks.fire_log(clear=True)
        with hooks.armed(FaultSpec("p", mode="error")):
            with pytest.raises(InjectedFaultError):
                hooks.fire("p", surface="x")
        log = hooks.fire_log()
        assert len(log) == 1 and log[0]["point"] == "p"
        assert _events_since(seq0, ["chaos_inject"])


# ===========================================================================
# fs layer
# ===========================================================================
class TestFsLayer:
    def test_enospc_replace_typed(self, tmp_path):
        src = tmp_path / "a"
        src.write_text("x")
        with hooks.armed(FaultSpec("fs.replace", mode="enospc")):
            with pytest.raises(StorageError) as ei:
                fslayer.replace(str(src), str(tmp_path / "b"),
                                surface="s")
        assert ei.value.op == "replace" and ei.value.surface == "s"
        assert isinstance(ei.value, OSError)  # except OSError still works
        assert src.exists()  # nothing moved

    def test_torn_append_leaves_half_line(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        fslayer.append_line(p, '{"a":1}\n', surface="t")
        with hooks.armed(FaultSpec("fs.append", mode="torn")):
            with pytest.raises(StorageError):
                fslayer.append_line(p, '{"b":2}\n', surface="t")
        lines = open(p).read().splitlines()
        assert lines[0] == '{"a":1}'
        assert 0 < len(lines[1]) < len('{"b":2}')

    def test_write_atomic_failure_cleans_staging(self, tmp_path):
        p = str(tmp_path / "meta.json")
        fslayer.write_atomic(p, "{}", surface="m")
        with hooks.armed(FaultSpec("fs.fsync", mode="eio")):
            with pytest.raises(StorageError):
                fslayer.write_atomic(p, '{"new": 1}', surface="m")
        assert open(p).read() == "{}"  # previous artifact intact
        assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]

    def test_append_after_torn_tail_repairs_not_merges(self, tmp_path):
        """A later append must NOT merge with a torn fragment (that
        would silently drop the new record on replay — or brick the
        journal once another record follows). The repair truncates the
        fragment, records journal_repair forensics, and every COMPLETE
        record before and after the tear replays."""
        p = str(tmp_path / "j.jsonl")
        fslayer.append_line(p, '{"a":1}\n', surface="t")
        with hooks.armed(FaultSpec("fs.append", mode="torn")):
            with pytest.raises(StorageError):
                fslayer.append_line(p, '{"b":2}\n', surface="t")
        seq0 = flight.default_flight_recorder().recorded_total
        fslayer.append_line(p, '{"c":3}\n', surface="t")
        fslayer.append_line(p, '{"d":4}\n', surface="t")
        lines = [json.loads(x) for x in open(p).read().splitlines()]
        assert lines == [{"a": 1}, {"c": 3}, {"d": 4}]
        assert _events_since(seq0, ["journal_repair"])

    def test_registry_survives_torn_append_then_more_publishes(
            self, tmp_path):
        """End to end on the registry journal: torn append → two MORE
        successful publishes → a fresh process replays everything that
        committed (no torn-middle refusal, no silently absorbed
        record)."""
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(chaos_drills._net(seed=1),
                             str(tmp_path / "ck1"))
        reg.publish("m", p1, score=0.5)
        with hooks.armed(FaultSpec(
                "fs.append", mode="torn",
                match={"surface": "registry_journal"})):
            with pytest.raises(StorageError):
                reg.publish("m", p1, score=0.4)
        reg.publish("m", p1, score=0.4)
        reg.publish("m", p1, score=0.39)
        reopened = ModelRegistry(str(tmp_path / "reg"))
        assert sorted(reopened.get("m")["versions"]) == ["1", "2", "3"]

    def test_storage_error_flight_event(self, tmp_path):
        seq0 = flight.default_flight_recorder().recorded_total
        with hooks.armed(FaultSpec("fs.replace", mode="enospc")):
            with pytest.raises(StorageError):
                fslayer.replace(str(tmp_path / "a"), str(tmp_path / "b"),
                                surface="s")
        evs = _events_since(seq0, ["storage_error"])
        assert evs and evs[-1]["op"] == "replace"


# ===========================================================================
# plans + seams
# ===========================================================================
class TestPlan:
    def test_json_round_trip(self):
        plan = ChaosPlan([{"seam": "fs.replace", "mode": "enospc",
                           "at_call": 3}], name="p", seed=9)
        again = ChaosPlan.from_json(plan.to_json())
        assert again.to_dict() == plan.to_dict()
        assert load_plan(plan.to_json()).name == "p"

    def test_unknown_seam_fails_fast(self):
        with pytest.raises(ValueError, match="unknown seam"):
            ChaosPlan([{"seam": "no.such.seam"}])

    def test_armed_context_arms_and_disarms(self):
        plan = ChaosPlan([{"seam": "serving.batch_dispatch",
                           "mode": "error"}])
        with plan.armed():
            assert "serving.batch_dispatch" in hooks.armed_points()
            with pytest.raises(InjectedFaultError):
                hooks.fire("serving.batch_dispatch")
        assert hooks.armed_points() == []

    def test_disarm_runs_even_when_workload_dies(self):
        plan = ChaosPlan([{"seam": "fs.fsync", "mode": "eio"}])
        with pytest.raises(RuntimeError, match="workload died"):
            with plan.armed():
                raise RuntimeError("workload died")
        assert hooks.armed_points() == []

    def test_on_event_trigger_fires_action_once(self):
        calls = []
        plan = ChaosPlan([{"seam": "on_event", "event": "ping",
                           "callback": lambda spec: calls.append(spec)}])
        with plan.armed():
            flight.record("other")
            flight.record("ping")
            flight.record("ping")  # times=1: second is ignored
        flight.record("ping")      # disarmed: observer removed
        assert len(calls) == 1

    def test_unknown_on_event_action(self):
        plan = ChaosPlan([{"seam": "on_event", "event": "x",
                           "action": "no_such_action"}])
        with pytest.raises(ValueError, match="unknown on_event action"):
            with plan.armed():
                pass


class TestInvariants:
    def test_event_order_subsequence(self):
        rep = InvariantReport()
        evs = [{"kind": k} for k in
               ["a", "noise", "b", "noise", "c"]]
        assert invariants.check_event_order(rep, evs, ["a", "b", "c"])
        assert not invariants.check_event_order(rep, evs, ["b", "a"])
        assert not rep.ok and len(rep.failures()) == 1

    def test_typed_errors_flags_bare_leaks(self):
        rep = InvariantReport()
        assert invariants.check_typed_errors(
            rep, [StorageError("x"), InjectedFaultError("y"),
                  ValueError("z")])
        rep2 = InvariantReport()
        assert not invariants.check_typed_errors(rep2, [KeyError("w")])
        assert "KeyError" in rep2.failures()[0].detail

    def test_no_tmp_litter_walks_nested(self, tmp_path):
        rep = InvariantReport()
        nested = tmp_path / "a" / "b"
        nested.mkdir(parents=True)
        assert invariants.check_no_tmp_litter(rep, str(tmp_path))
        (nested / "x.zip.tmp-123-cafe").write_text("junk")
        assert not invariants.check_no_tmp_litter(rep, str(tmp_path))


# ===========================================================================
# disk-full hardening satellites (typed + cleanup + previous intact)
# ===========================================================================
class TestDiskFullHardening:
    def test_registry_memory_matches_disk_after_failed_append(
            self, tmp_path):
        """The WAL append fails → NOTHING is folded in memory: the same
        registry object (no re-open) still resolves v1, and the NEXT
        publish succeeds and takes version 2 (no version-number hole)."""
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.train.faults import save_checkpoint

        net = chaos_drills._net(seed=1)
        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(net, str(tmp_path / "ck1"))
        reg.publish("m", p1, score=0.5)
        with hooks.armed(FaultSpec(
                "fs.append", mode="enospc",
                match={"surface": "registry_journal"})):
            with pytest.raises(StorageError):
                reg.publish("m", p1, score=0.4)
        assert reg.resolve("m")["version"] == 1
        assert list(reg.get("m")["versions"]) == ["1"]
        rec = reg.publish("m", p1, score=0.4)
        assert rec["version"] == 2

    def test_failed_fsync_append_cannot_resurrect_on_replay(
            self, tmp_path):
        """A failed journal-append FSYNC leaves the whole flushed line
        behind unless rolled back — a publish the caller was told
        failed must not reappear (pointing at a deleted snapshot) when
        a fresh process replays."""
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(chaos_drills._net(seed=1),
                             str(tmp_path / "ck1"))
        reg.publish("m", p1, score=0.5)
        with hooks.armed(FaultSpec(
                "fs.fsync", mode="eio",
                match={"path_substr": "journal.jsonl"})):
            with pytest.raises(StorageError):
                reg.publish("m", p1, score=0.4)
        reopened = ModelRegistry(str(tmp_path / "reg"))
        assert sorted(reopened.get("m")["versions"]) == ["1"]
        # and the live object's byte accounting still matches the file:
        # the next publish commits cleanly as v2
        assert reg.publish("m", p1, score=0.4)["version"] == 2

    def test_first_publish_failed_append_leaves_no_phantom_model(
            self, tmp_path):
        """A FIRST publish whose WAL append fails must not leave an
        in-memory model entry no restart would replay (memory ≡ disk)."""
        from deeplearning4j_tpu.serving.registry import (
            ModelRegistry,
            UnknownModelError,
        )
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(chaos_drills._net(seed=1),
                             str(tmp_path / "ck1"))
        with hooks.armed(FaultSpec(
                "fs.append", mode="enospc",
                match={"surface": "registry_journal"})):
            with pytest.raises(StorageError):
                reg.publish("m", p1, score=0.5)
        assert reg.models() == []
        with pytest.raises(UnknownModelError):
            reg.get("m")
        rec = reg.publish("m", p1, score=0.5)  # clean retry: v1, active
        assert rec["version"] == 1 and rec["status"] == "active"

    def test_registry_snapshot_write_failure_degrades_not_fails(
            self, tmp_path):
        """registry.json is the convenience mirror, the journal is the
        WAL: a failed snapshot rewrite warns and degrades, and replay
        still sees the committed record."""
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(chaos_drills._net(seed=1),
                             str(tmp_path / "ck1"))
        with hooks.armed(FaultSpec(
                "fs.replace", mode="enospc",
                match={"surface": "registry_snapshot"}, times=None)):
            with pytest.warns(UserWarning, match="snapshot write failed"):
                reg.publish("m", p1, score=0.5)
        reopened = ModelRegistry(str(tmp_path / "reg"))
        assert reopened.resolve("m")["version"] == 1

    def test_tune_store_meta_enospc_previous_intact(self, tmp_path):
        from deeplearning4j_tpu.tune.store import TrialStore

        store = TrialStore(str(tmp_path / "study"))
        store.write_meta({"v": 1})
        with hooks.armed(FaultSpec("fs.replace", mode="enospc",
                                   match={"surface": "tune_meta"})):
            with pytest.raises(StorageError):
                store.write_meta({"v": 2})
        assert store.read_meta() == {"v": 1}
        assert not [n for n in os.listdir(tmp_path / "study")
                    if ".tmp-" in n]

    def test_checkpoint_write_failure_keeps_fingerprint(self, tmp_path):
        """The visible checkpoint's bytes are untouched by a failed
        rewrite — fingerprint-identical, not merely loadable."""
        from deeplearning4j_tpu.train import faults

        net = chaos_drills._net(seed=2)
        ck = str(tmp_path / "ck")
        path = faults.save_checkpoint(net, ck, stem="only")
        fp = faults.checkpoint_fingerprint(path)
        with hooks.armed(FaultSpec("fs.fsync", mode="eio",
                                   match={"surface": "checkpoint"})):
            with pytest.raises(StorageError):
                faults.save_checkpoint(net, ck, stem="only")
        assert faults.checkpoint_fingerprint(path) == fp


class TestTmpSweep:
    def _plant(self, directory, age_s=3600.0):
        os.makedirs(directory, exist_ok=True)
        import time

        p = os.path.join(directory, "ck.zip.tmp-1-dead")
        open(p, "w").write("junk")
        old = time.time() - age_s
        os.utime(p, (old, old))
        return p

    def test_checkpoint_listener_open_sweeps_and_counts(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        d = str(tmp_path / "ck")
        stale = self._plant(d)
        fresh = os.path.join(d, "live.zip.tmp-2-beef")
        open(fresh, "w").write("inflight")
        seq0 = flight.default_flight_recorder().recorded_total
        CheckpointListener(d, save_every_n_epochs=1)
        assert not os.path.exists(stale)
        assert os.path.exists(fresh)  # young: may be a live writer
        evs = _events_since(seq0, ["tmp_sweep"])
        assert evs and evs[-1]["count"] == 1

    def test_registry_open_sweeps_snapshot_staging(self, tmp_path):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        d = str(tmp_path / "reg")
        stale = self._plant(os.path.join(d, "snapshots", "m"))
        ModelRegistry(d)
        assert not os.path.exists(stale)

    def test_tune_store_open_sweeps(self, tmp_path):
        from deeplearning4j_tpu.tune.store import TrialStore

        d = str(tmp_path / "study")
        stale = self._plant(d)
        TrialStore(d)
        assert not os.path.exists(stale)


# ===========================================================================
# the generation → canary gate residue (PR 11)
# ===========================================================================
class TestGenerationCanaryGate:
    def _registry(self, tmp_path, window_s):
        from deeplearning4j_tpu.serving.registry import (
            ModelRegistry,
            ModelRouter,
        )
        from deeplearning4j_tpu.train.faults import save_checkpoint

        reg = ModelRegistry(str(tmp_path / "reg"))
        p1 = save_checkpoint(chaos_drills._lstm(seed=1),
                             str(tmp_path / "ck1"))
        p2 = save_checkpoint(chaos_drills._lstm(seed=2),
                             str(tmp_path / "ck2"))
        reg.publish("lm", p1, score=0.5)
        router = ModelRouter(reg, gen_slots=2, gen_max_length=16,
                             canary_fraction=0.5, canary_window_s=window_s,
                             canary_min_requests=1, refresh_s=0.0)
        return reg, router, p2

    def test_generation_only_traffic_promotes_clean_canary(
            self, tmp_path):
        import time

        reg, router, p2 = self._registry(tmp_path, window_s=0.3)
        try:
            prompt = np.array([1, 2, 3], np.int32)
            router.generation_submit("lm", prompt, max_new=3,
                                     timeout=30).result(timeout=30)
            reg.publish("lm", p2, score=0.48)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                router.generation_submit("lm", prompt, max_new=3,
                                         timeout=30).result(timeout=30)
                if reg.get("lm").get("active_version") == 2:
                    break
                time.sleep(0.02)
            assert reg.get("lm").get("active_version") == 2
            # per-version generation counters exist in the shared registry
            fams = router.metrics.registry.family_values(
                "registry_version_gen_requests_total")
            assert any(v > 0 for v in fams.values())
        finally:
            router.shutdown()

    def test_router_shutdown_with_live_canary_generation_no_deadlock(
            self, tmp_path):
        """Router shutdown joins generation workers whose completion
        observers take mm.lock — teardown must happen OUTSIDE the lock
        or a completion racing shutdown deadlocks the process. Runs
        under the STRICT lock witness (obs/lockwitness.py): the PR 13
        bug was exactly an acquisition-order inversion between
        router.model and the generation engine's locks, so beyond
        not-hanging, the order graph itself must stay acyclic."""
        lockwitness.reset()
        cycles0 = len(lockwitness.cycles())
        with lockwitness.armed(strict=True):
            reg, router, p2 = self._registry(tmp_path, window_s=60.0)
            prompt = np.array([1, 2, 3], np.int32)
            router.generation_submit("lm", prompt, max_new=3,
                                     timeout=30).result(timeout=30)
            reg.publish("lm", p2, score=0.48)
            # open the window and put generation traffic in flight on
            # BOTH engines, then shut down while completions are landing
            reqs = [router.generation_submit("lm", prompt, max_new=5,
                                             timeout=30)
                    for _ in range(6)]
            done = {"ok": False}

            def _shutdown():
                router.shutdown()
                done["ok"] = True

            t = threading.Thread(target=_shutdown, daemon=True)
            t.start()
            t.join(timeout=60)
            assert done["ok"], "router.shutdown deadlocked"
            for r in reqs:
                try:
                    r.result(timeout=5)  # served or failed typed
                except Exception:
                    pass
        assert lockwitness.cycles()[cycles0:] == [], (
            "shutdown path reintroduced a lock-order inversion")

    def test_generation_only_regression_trips_rollback(self, tmp_path):
        reg, router, p2 = self._registry(tmp_path, window_s=60.0)
        try:
            prompt = np.array([1, 2, 3], np.int32)
            router.generation_submit("lm", prompt, max_new=3,
                                     timeout=30).result(timeout=30)
            reg.publish("lm", p2, score=0.48)
            seq0 = flight.default_flight_recorder().recorded_total
            spec = FaultSpec("generate.decode_dispatch", mode="error",
                             match={"role": "canary"}, times=None)
            rolled = False
            with hooks.armed(spec):
                for _ in range(16):
                    req = router.generation_submit("lm", prompt,
                                                   max_new=3, timeout=30)
                    try:
                        req.result(timeout=30)
                    except (InjectedFaultError, Exception):
                        pass
                    if (reg.get("lm")["versions"].get("2", {})
                            .get("status") == "rolled_back"):
                        rolled = True
                        break
            assert rolled
            kinds = [e["kind"] for e in _events_since(seq0)]
            assert "regression_trip" in kinds and "rollback" in kinds
            # active generation keeps serving after the rollback
            out = router.generation_submit(
                "lm", prompt, max_new=3, timeout=30).result(timeout=30)
            assert out is not None
        finally:
            router.shutdown()


# ===========================================================================
# install_signal_dump SIGTERM drill (satellite)
# ===========================================================================
class TestSignalDump:
    def test_sigterm_mid_fit_dumps_ordered_and_chains(self, tmp_path):
        """SIGTERM lands mid-fit: the black box is dumped (step events
        then the signal event, seq-ordered), and the PREVIOUSLY
        installed handler still runs (chaining)."""
        from deeplearning4j_tpu.obs.flight import (
            FlightRecorderListener,
            install_signal_dump,
        )

        rec = flight.default_flight_recorder()
        chained = []
        prev = signal.signal(signal.SIGTERM,
                             lambda s, f: chained.append(s))
        uninstall = None
        try:
            uninstall = install_signal_dump()
            box = str(tmp_path / "box")
            model = chaos_drills._net()
            model.add_listeners(FlightRecorderListener(
                directory=box, loss_frequency=1, dump_every_s=None))

            class _Bomb:
                requires_per_step_state = True

                def iteration_done(self, m, iteration, epoch):
                    if iteration == 2:
                        os.kill(os.getpid(), signal.SIGTERM)

            model.add_listeners(_Bomb())
            from deeplearning4j_tpu.data import ExistingDataSetIterator

            model.fit(ExistingDataSetIterator(chaos_drills._batches(4)))
            assert chained == [signal.SIGTERM]  # chained to prev handler
            dumps = [n for n in os.listdir(box)
                     if n.startswith("flight_recorder_")]
            assert dumps
            with open(os.path.join(box, dumps[0])) as f:
                body = json.load(f)
            kinds = [e["kind"] for e in body["events"]]
            sig_at = kinds.index("signal")
            assert "step" in kinds[:sig_at]  # mid-fit: steps precede it
            seqs = [e["seq"] for e in body["events"]]
            assert seqs == sorted(seqs)
            # the fit completed after the signal, so the final dump's
            # reason is fit_end — the freshest superset (one black box
            # per process); the signal dump preceded it and its events
            # are all still inside
            assert body["reason"] in ("fit_end", "signal_15")
        finally:
            if uninstall is not None:
                uninstall()
            signal.signal(signal.SIGTERM, prev)
            rec.clear()


# ===========================================================================
# the drill matrix
# ===========================================================================
_FAST_DRILLS = [n for n, d in chaos_drills.DRILLS.items() if d.fast]
_PAIRED_DRILLS = [n for n, d in chaos_drills.DRILLS.items() if d.paired]


class TestDrillMatrix:
    def test_matrix_floor(self):
        assert len(chaos_drills.DRILLS) >= 12
        assert len(_PAIRED_DRILLS) >= 3

    @pytest.mark.parametrize("name", _FAST_DRILLS)
    def test_fast_drill_green(self, name):
        r = chaos_drills.run_drill(name)
        assert r.skipped is None, r.skipped  # 8-device mesh available
        assert r.error is None, r.error
        assert r.ok, json.dumps([c for c in r.checks if not c["ok"]],
                                indent=1)

    def test_unknown_drill_typed(self):
        with pytest.raises(ValueError, match="unknown drill"):
            chaos_drills.run_drill("no_such_drill")
        with pytest.raises(ValueError, match="unknown drill"):
            chaos_drills.run_matrix(names=["no_such_drill"])

    def test_expected_alerts_coverage_floor(self):
        """ISSUE 15: detection is part of the matrix contract — at
        least 8 drills declare expected_alerts, and every declared
        name is in the obs/events.py ALERTS schema."""
        from deeplearning4j_tpu.obs import events as obs_events

        covered = [d for d in chaos_drills.DRILLS.values()
                   if d.expected_alerts]
        assert len(covered) >= 8, [d.name for d in covered]
        for d in covered:
            for a in d.expected_alerts:
                assert obs_events.is_declared_alert(a), (d.name, a)

    def test_drill_detection_rides_scorecard(self):
        """A drill's injected fault must trip exactly the alert that
        claims to cover it, and the scorecard must say so (per-drill
        alerts_fired + matrix-level alerts_verified)."""
        out = chaos_drills.run_matrix(
            names=["checkpoint_fsync_fail", "registry_nan_publish_gate"])
        assert out["ok"], json.dumps(out["drills"], indent=1)
        by_name = {d["drill"]: d for d in out["drills"]}
        assert "storage_errors" in \
            by_name["checkpoint_fsync_fail"]["alerts_fired"]
        assert "publish_refused" in \
            by_name["registry_nan_publish_gate"]["alerts_fired"]
        assert by_name["checkpoint_fsync_fail"]["expected_alerts"] == \
            ["storage_errors"]
        assert out["alerts_verified"] == 2
        checks = {c["name"] for d in out["drills"]
                  for c in d["checks"]}
        assert "expected_alerts_fired" in checks

    def test_missing_expected_alert_is_red(self):
        """An expected alert that never fires must fail the drill —
        the detection check cannot pass vacuously."""
        from deeplearning4j_tpu.chaos.invariants import (
            InvariantReport,
            check_expected_alerts,
        )

        rep = InvariantReport()
        assert not check_expected_alerts(
            rep, fired=["storage_errors"],
            expected=["storage_errors", "decode_stalled"])
        assert "decode_stalled" in rep.failures()[0].detail
        rep2 = InvariantReport()
        assert check_expected_alerts(
            rep2, fired=["a", "b"], expected=["a"])

    def test_explicit_names_bypass_fast_filter(self):
        """--fast --drill <paired> must RUN the paired drill, not
        silently select zero drills and exit green."""
        name = _PAIRED_DRILLS[0]
        out = chaos_drills.run_matrix(fast_only=True, names=[name])
        assert out["n_drills"] == 1
        assert out["drills"][0]["drill"] == name

    def test_gen_observer_installed_before_enqueue(self):
        """The canary gate's completion observer must ride in through
        submit (set before the worker can complete the request) — an
        instant completion racing the submit return is still counted."""
        import inspect

        from deeplearning4j_tpu.serving.generate import GenerationEngine

        assert "on_done" in inspect.signature(
            GenerationEngine.submit).parameters

    def test_run_custom_plan_over_workload(self):
        # tear the LAST append: a torn TRAILING line is the crash state
        # replay absorbs (a torn middle is refused by design)
        plan = ChaosPlan([{"seam": "fs.append", "mode": "torn",
                           "at_call": 4,
                           "match": {"surface": "tune_journal"}}])
        r = chaos_drills.run_custom(plan, "tune")
        assert r.ok, json.dumps(r.checks, indent=1)

    def test_cli_chaos_list_and_single_drill(self, capsys):
        from deeplearning4j_tpu.cli import chaos_main

        assert chaos_main(["--list"]) == 0
        out = capsys.readouterr().out
        assert "generate.decode_dispatch" in out
        assert "paired_watchdog_trip_during_canary" in out
        assert chaos_main(["--drill", "tune_journal_torn",
                           "--out", ""]) == 0


@pytest.mark.slow
class TestPairedStorms:
    @pytest.mark.parametrize("name", _PAIRED_DRILLS)
    def test_paired_drill_green(self, name):
        r = chaos_drills.run_drill(name)
        assert r.skipped is None, r.skipped
        assert r.error is None, r.error
        assert r.ok, json.dumps([c for c in r.checks if not c["ok"]],
                                indent=1)