"""Distributed TransformerLM: dp/tp/pp/sp parity on the 8-device CPU mesh.

Models the reference's distributed-parity test pattern
(``TestCompareParameterAveragingSparkVsSingleMachine.java``, SURVEY.md
§4.5: train the same net both ways, compare) — here for all four
parallelism axes, which the reference lacks entirely.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer_lm import TransformerLM
from deeplearning4j_tpu.parallel.mesh import TrainingMesh
from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

V, T, B = 31, 16, 8


def _data(seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, (B, T)).astype(np.int32)
    tgt = np.roll(ids, -1, axis=1).astype(np.int32)
    tgt[:, -1] = -1
    return ids, tgt


def _model():
    return TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=4,
                         max_length=T).init()


MESHES = [
    ("dp8", dict(data=8), 1),
    ("dp2_tp4", dict(data=2, model=4), 1),
    ("dp2_sp4", dict(data=2, seq=4), 1),
    ("dp2_pp4", dict(data=2, pipe=4), 4),
    ("dp2_tp2_pp2", dict(data=2, model=2, pipe=2), 4),
    ("tp2_pp2_sp2", dict(data=1, model=2, pipe=2, seq=2), 4),
]


class TestDistributedParity:
    @pytest.fixture(scope="class")
    def reference_losses(self):
        """3 steps of single-device training."""
        m = _model()
        ids, tgt = _data()
        return [m.fit_batch(ids, tgt) for _ in range(3)]

    @pytest.mark.parametrize("name,mesh_kw,n_micro", [
        m if m[0] == "dp8" else pytest.param(*m, marks=pytest.mark.slow)
        for m in MESHES
    ], ids=[m[0] for m in MESHES])
    def test_matches_single_device(self, name, mesh_kw, n_micro,
                                   reference_losses):
        m = _model()
        mesh = TrainingMesh(**mesh_kw)
        tr = DistributedLMTrainer(m, mesh, n_micro=n_micro).place()
        ids, tgt = _data()
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(losses, reference_losses, rtol=2e-3,
                                   atol=1e-4)

    @pytest.mark.slow
    def test_training_converges_distributed(self):
        """Full 3-axis mesh learns the next-token copy structure."""
        m = _model()
        mesh = TrainingMesh(data=2, model=2, seq=2)
        tr = DistributedLMTrainer(m, mesh).place()
        ids, tgt = _data()
        first = tr.fit_batch(ids, tgt)
        for _ in range(30):
            last = tr.fit_batch(ids, tgt)
        assert last < first * 0.5, f"distributed training stalled: {first}->{last}"


class TestAwkwardShapes:
    """VERDICT r3 item 8: realistic-ish sharding shapes beyond the
    toy powers of two — TP with head dims nowhere near a multiple of
    128, and a deeper pipeline with n_micro=8."""

    @pytest.mark.slow
    def test_tp2_non_multiple_of_128_head_dim(self):
        """d_model=40, 2 heads → head_dim=20; per-TP-shard 1 head of 20.
        The sharding arithmetic must not assume MXU-friendly multiples —
        parity vs single device is the proof."""
        def model():
            return TransformerLM(vocab_size=V, d_model=40, n_heads=2,
                                 n_layers=2, max_length=T).init()

        ids, tgt = _data()
        ref = model()
        ref_losses = [ref.fit_batch(ids, tgt) for _ in range(3)]
        tr = DistributedLMTrainer(model(), TrainingMesh(data=2, model=2,
                                  devices=jax.devices()[:4])).place()
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=1e-4)

    @pytest.mark.slow
    @pytest.mark.parametrize("mesh_kw,n_micro", [
        (dict(data=2, pipe=4), 4), (dict(data=8), 1)],
        ids=["pp4", "dp-only"])
    def test_remat_blocks_parity(self, mesh_kw, n_micro):
        """remat_blocks recomputes block interiors in backward — same
        math, bounded activation memory, on pipelined AND plain meshes;
        losses must match the default exactly."""
        ids, tgt = _data()
        base = DistributedLMTrainer(_model(), TrainingMesh(**mesh_kw),
                                    n_micro=n_micro).place()
        base_losses = [base.fit_batch(ids, tgt) for _ in range(3)]
        rem = DistributedLMTrainer(_model(), TrainingMesh(**mesh_kw),
                                   n_micro=n_micro,
                                   remat_blocks=True).place()
        rem_losses = [rem.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(rem_losses, base_losses, rtol=1e-6)

    @pytest.mark.slow
    def test_pp2_n_micro8_parity_and_bubble_fraction(self):
        """GPipe with 8 microbatches: parity holds and the schedule
        reports its idle fraction (pp-1)/(n_micro+pp-1)."""
        ids, tgt = _data()
        ref = _model()
        ref_losses = [ref.fit_batch(ids, tgt) for _ in range(3)]
        tr = DistributedLMTrainer(_model(), TrainingMesh(data=2, pipe=4),
                                  n_micro=8).place()
        assert abs(tr.bubble_fraction - 3 / 11) < 1e-9
        losses = [tr.fit_batch(ids, tgt) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=1e-4)
        # no pipelining → no bubble
        assert DistributedLMTrainer(
            _model(), TrainingMesh(data=8)).bubble_fraction == 0.0


class TestTransformerLMSingle:
    def test_generate_and_logits(self):
        m = _model()
        ids, tgt = _data()
        for _ in range(5):
            m.fit_batch(ids, tgt)
        logits = m.logits(ids[:2])
        assert logits.shape == (2, T, V)
        gen = m.generate(ids[0, :4], max_new=5)
        assert gen.shape == (1, 9)
        assert np.all((gen >= 0) & (gen < V))

    def test_causality(self):
        """Logit at position t is independent of tokens after t."""
        m = _model()
        ids, _ = _data()
        a = m.logits(ids[:1])
        ids2 = ids[:1].copy()
        ids2[0, 10:] = (ids2[0, 10:] + 1) % V
        b = m.logits(ids2)
        np.testing.assert_allclose(a[0, :10], b[0, :10], rtol=1e-4, atol=1e-5)

    def test_layer_count_divisibility_check(self):
        m = TransformerLM(vocab_size=V, d_model=32, n_heads=4, n_layers=3,
                          max_length=T).init()
        with pytest.raises(ValueError, match="not divisible"):
            DistributedLMTrainer(m, TrainingMesh(data=4, pipe=2))


class TestScanRolledPipeline:
    def test_many_microbatches_compile_quickly(self):
        """The scan-rolled GPipe schedule is O(1) in microbatch count
        (round-2 weakness: Python-unrolled compile scaled with M+pp).
        M=32 microbatches must work and match the M=4 result."""
        import time

        losses, compile_s = {}, {}
        for m in (4, 32):
            model = _model()
            mesh = TrainingMesh(data=1, model=1, pipe=2, seq=1,
                                devices=jax.devices()[:2])
            tr = DistributedLMTrainer(model, mesh, n_micro=m)
            tr.place()
            rng = np.random.default_rng(0)
            ids = rng.integers(0, V, (64, T)).astype(np.int32)
            tgt = np.roll(ids, -1, axis=1).astype(np.int32)
            tgt[:, -1] = -1
            t0 = time.perf_counter()
            losses[m] = tr.fit_batch(ids, tgt)  # includes compile
            compile_s[m] = time.perf_counter() - t0
        # same data, same params → same loss regardless of microbatching
        np.testing.assert_allclose(losses[4], losses[32], rtol=2e-3)
        # compile is O(1) in M: 8x microbatches must not blow up compile
        # time (the unrolled schedule scaled ~linearly in M+pp)
        assert compile_s[32] < 3.0 * compile_s[4] + 2.0, compile_s


class TestGradientClipping:
    def test_clip_norm_bounds_update_magnitude(self):
        """clip_norm must cap the global gradient norm: with a tiny clip
        the first-step parameter change is proportionally tiny."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer_lm import TransformerLM
        from deeplearning4j_tpu.parallel import TrainingMesh
        from deeplearning4j_tpu.parallel.transformer import DistributedLMTrainer

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 32, (8, 8)).astype(np.int32)
        tgt = np.roll(ids, -1, 1).astype(np.int32)
        tgt[:, -1] = -1

        from deeplearning4j_tpu.updaters import Sgd

        def delta(clip):
            # SGD: update magnitude proportional to the (clipped) gradient
            # (Adam's first step is gradient-scale invariant)
            m = TransformerLM(vocab_size=32, d_model=32, n_heads=4,
                              n_layers=2, max_length=8, seed=6,
                              updater=Sgd(0.1)).init()
            before = np.asarray(m.params_["head"]).copy()
            tr = DistributedLMTrainer(m, TrainingMesh(data=8),
                                      clip_norm=clip).place()
            tr.fit_batch(ids, tgt)
            return float(np.abs(np.asarray(m.params_["head"]) - before).max())

        d_unclipped = delta(None)
        d_clipped = delta(1e-3)
        assert d_clipped < d_unclipped / 10, (d_clipped, d_unclipped)
        # generous clip leaves the step effectively untouched
        d_loose = delta(1e6)
        np.testing.assert_allclose(d_loose, d_unclipped, rtol=1e-5)
