"""Fused-kernel layer (ISSUE 12, nn/ops/): KernelRegistry contract,
fused LSTM cell, fused ZeRO-1 update, int8 serving matmul.

Tier-1 runs everything on the CPU mesh: the kernels execute through the
Pallas INTERPRETER (``DL4J_TPU_*=interpret`` — real kernel math, XLA
execution), the fallback paths run natively, and forced probe failures
assert the fallback contract. Mosaic-compiled variants (real TPU) live
in the ``slow``/TPU-gated class at the bottom — the axon tunnel is not
reachable from tier-1.

Parity contract asserted here (and documented in ARCHITECTURE.md):
- LSTM cell: forward BIT-exact vs the reference step at fp32 (aligned
  AND lane-padded shapes); grads ≤ 1e-5; bf16 ≤ 2e-2.
- ZeRO-1 fused update: BIT-exact params + Adam slots vs the unfused
  step, including odd-count padding groups.
- int8 matmul: kernel ≡ XLA reference bit-exact at fp32; quantized vs
  f32 serving bounded by the per-channel quantization error (top-1
  agreement on zoo-style heads).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.ops import fused_lstm, fused_update, int8_matmul
from deeplearning4j_tpu.nn.ops.registry import (
    ENV_FLAGS,
    KernelRegistry,
    default_kernel_registry,
    kernel_route,
)


@pytest.fixture
def kernel_env(monkeypatch):
    """Force a kernel mode for one test and leave the process-global
    registry clean afterwards (the registry caches per-process; a test
    must not leak its mode into the rest of the suite)."""
    touched = []

    def set_mode(name, mode):
        monkeypatch.setenv(ENV_FLAGS[name], mode)
        default_kernel_registry().reset(name)
        touched.append(name)

    yield set_mode
    for name in touched:
        default_kernel_registry().reset(name)


def _rand(shape, seed=0, dtype=np.float32):
    return np.asarray(np.random.default_rng(seed).standard_normal(shape),
                      dtype)


# ==========================================================================
# registry
# ==========================================================================
class TestKernelRegistry:
    def test_probe_once_per_process(self):
        reg = KernelRegistry()
        calls = []

        def probe():
            calls.append(1)

        assert reg.probe("fused_lstm", ("k",), probe) is True
        assert reg.probe("fused_lstm", ("k",), probe) is True
        assert len(calls) == 1  # second resolution is a cache hit

    def test_failed_probe_caches_and_reports(self):
        from deeplearning4j_tpu.obs import flight

        reg = KernelRegistry()
        calls = []

        def probe():
            calls.append(1)
            raise RuntimeError("Mosaic reject: Bad lhs type")

        n_before = len(flight.default_flight_recorder())
        assert reg.probe("fused_lstm", ("bad",), probe) is False
        assert reg.probe("fused_lstm", ("bad",), probe) is False
        assert len(calls) == 1  # deterministic reject: exactly one attempt
        events = flight.default_flight_recorder().events()
        new = [e for e in events if e["kind"] == "kernel_fallback"]
        assert any("Bad lhs type" in e.get("reason", "") for e in new)
        assert len(flight.default_flight_recorder()) > n_before

    def test_concurrent_same_key_probes_run_once(self):
        """Probes run OUTSIDE the registry lock; same-key racers wait on
        the in-flight probe instead of compiling twice."""
        import threading
        import time

        reg = KernelRegistry()
        calls = []

        def probe():
            calls.append(1)
            time.sleep(0.15)

        results = []
        threads = [threading.Thread(
            target=lambda: results.append(
                reg.probe("fused_lstm", ("race",), probe)))
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == [True] * 4
        assert len(calls) == 1

    def test_fused_conv_kill_switch(self, kernel_env):
        from deeplearning4j_tpu.nn.ops import fused_conv

        kernel_env("fused_conv", "0")
        fused_conv._PROBE_CACHE.clear()
        try:
            assert fused_conv.fused_conv_available(jnp.bfloat16) is False
            snap = default_kernel_registry().snapshot()["fused_conv"]
            assert any("DL4J_TPU_FUSED_CONV=0" in v["reason"]
                       for v in snap.values())
        finally:
            fused_conv._PROBE_CACHE.clear()

    def test_transient_failure_retried(self):
        reg = KernelRegistry()
        calls = []

        def probe():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("tpu_compile_helper subprocess exit "
                                   "code 1")

        assert reg.probe("fused_conv", ("flaky",), probe) is True
        assert len(calls) == 2

    def test_enabled_gauge_on_default_metrics(self):
        from deeplearning4j_tpu.obs.metrics import default_registry

        reg = KernelRegistry()
        reg.probe("int8_matmul", ("g1",), lambda: None)
        g = default_registry().get("kernel_enabled",
                                   labels={"name": "int8_matmul"})
        assert g is not None and g.value() == 1.0

    def test_env_kill_switch(self, kernel_env):
        kernel_env("fused_lstm", "0")
        assert kernel_route("fused_lstm", ("any",)) is None
        assert default_kernel_registry().enabled(
            "fused_lstm", ("any",)) is False

    def test_auto_mode_disables_off_tpu(self):
        reg = default_kernel_registry()
        reg.reset("fused_lstm")
        assert kernel_route("fused_lstm", ("cpukey",)) is None
        snap = reg.snapshot()["fused_lstm"]
        assert any("non-TPU backend" in v["reason"] for v in snap.values())
        reg.reset("fused_lstm")

    def test_interpret_mode_routes(self, kernel_env):
        kernel_env("fused_lstm", "interpret")
        assert kernel_route("fused_lstm", ("ik",)) is True


# ==========================================================================
# fused LSTM cell
# ==========================================================================
class TestFusedLSTMCell:
    @pytest.mark.parametrize("n_in,n", [(128, 128), (77, 256), (64, 96)])
    @pytest.mark.parametrize("peephole", [False, True])
    def test_forward_bit_exact_fp32(self, n_in, n, peephole):
        B = 8
        x, h, c = (_rand((B, d), i) for i, d in
                   enumerate((n_in, n, n)))
        Wx, Wh, b = _rand((n_in, 4 * n), 3), _rand((n, 4 * n), 4), \
            _rand((4 * n,), 5)
        peeps = ((_rand((n,), 6), _rand((n,), 7), _rand((n,), 8))
                 if peephole else ())
        args = tuple(jnp.asarray(a) for a in (x, h, c, Wx, Wh, b) + peeps)
        # jit both legs: that is how every real caller runs them (eager
        # op-by-op dispatch takes a different gemm path than the
        # compiled program and is ~1e-7 off EITHER compiled leg)
        hf, cf = jax.jit(lambda *a: fused_lstm.fused_lstm_cell(
            *a, interpret=True))(*args)
        hr, cr = jax.jit(fused_lstm.reference_lstm_cell)(*args)
        np.testing.assert_array_equal(np.asarray(hf), np.asarray(hr))
        np.testing.assert_array_equal(np.asarray(cf), np.asarray(cr))

    def test_gradients_close(self):
        n_in, n, B = 64, 96, 8
        args = tuple(jnp.asarray(a) for a in (
            _rand((B, n_in), 0), _rand((B, n), 1), _rand((B, n), 2),
            _rand((n_in, 4 * n), 3), _rand((n, 4 * n), 4),
            _rand((4 * n,), 5), _rand((n,), 6), _rand((n,), 7),
            _rand((n,), 8)))

        def loss(cell):
            def f(*a):
                hn, cn = cell(*a)
                return jnp.sum(hn ** 2) + jnp.sum(cn ** 2)
            return f

        gf = jax.grad(loss(lambda *a: fused_lstm.fused_lstm_cell(
            *a, interpret=True)), argnums=tuple(range(9)))(*args)
        gr = jax.grad(loss(fused_lstm.reference_lstm_cell),
                      argnums=tuple(range(9)))(*args)
        for i, (a, b) in enumerate(zip(gf, gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"grad[{i}]")

    def test_bf16_documented_tolerance(self):
        n_in = n = 128
        B = 8
        mk = lambda s, i: jnp.asarray(_rand(s, i)).astype(jnp.bfloat16)
        args = (mk((B, n_in), 0), mk((B, n), 1), mk((B, n), 2),
                mk((n_in, 4 * n), 3), mk((n, 4 * n), 4), mk((4 * n,), 5))
        hf, cf = fused_lstm.fused_lstm_cell(*args, interpret=True)
        hr, cr = fused_lstm.reference_lstm_cell(*args)
        err = np.max(np.abs(np.asarray(hf, np.float32)
                            - np.asarray(hr, np.float32)))
        assert err <= 2e-2  # one MXU pass vs "highest" XLA: documented

    def test_layer_scan_parity_fused_vs_reference(self, kernel_env):
        """Full-sequence apply_with_carry through the fused cell
        (interpret) vs the reference scan: the isolated cell is
        bit-exact, but inside the scan body XLA fuses the surrounding
        ops differently per leg (FMA/epilogue reassociation) — the
        documented full-sequence tolerance is ≤1e-6 absolute at fp32
        (T=1 decode, the latency path, IS bit-exact — see
        TestLSTMDecodeCellPath)."""
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers.recurrent import GravesLSTM

        layer = GravesLSTM(n_out=64, n_in=32, activation="tanh")
        layer.initialize(InputType.recurrent(32))
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.recurrent(32))
        x = jnp.asarray(_rand((4, 12, 32), 1))
        carry = layer.init_carry(4)
        y_ref, c_ref = jax.jit(
            lambda p, x, c: layer.apply_with_carry(p, x, c))(params, x,
                                                             carry)
        kernel_env("fused_lstm", "interpret")
        y_f, c_f = jax.jit(
            lambda p, x, c: layer.apply_with_carry(p, x, c))(params, x,
                                                             carry)
        snap = default_kernel_registry().snapshot()["fused_lstm"]
        assert any(v["enabled"] for v in snap.values())
        np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_ref),
                                   rtol=0, atol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(c_f),
                        jax.tree_util.tree_leaves(c_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=1e-6)

    def test_training_fit_parity(self, kernel_env):
        """3 fit steps of the textgen-style stack, fused(interpret) vs
        reference: params within the backward-recompute tolerance (the
        fused backward recomputes gates — same math, XLA op order)."""
        from deeplearning4j_tpu.models.textgen_lstm import (
            TextGenerationLSTM,
        )

        def fit_one():
            m = TextGenerationLSTM(num_classes=11, units=32,
                                   max_length=8).init()
            X = _rand((4, 8, 11), 0)  # (batch, time, vocab) one-hot-ish
            y = np.abs(_rand((4, 8, 11), 1))
            y = y / np.sum(y, axis=-1, keepdims=True)
            for _ in range(3):
                m.fit(X, y.astype(np.float32))
            return m.params_

    # reference leg first (default env: auto → CPU fallback)
        p_ref = fit_one()
        kernel_env("fused_lstm", "interpret")
        p_f = fit_one()
        for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                        jax.tree_util.tree_leaves(p_f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_forced_probe_failure_falls_back_identical(self, kernel_env,
                                                       monkeypatch):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM
        from deeplearning4j_tpu.obs import flight

        layer = LSTM(n_out=16, n_in=8, activation="tanh")
        layer.initialize(InputType.recurrent(8))
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.recurrent(8))
        x = jnp.asarray(_rand((2, 5, 8), 2))
        carry = layer.init_carry(2)
        y_ref, _ = layer.apply_with_carry(params, x, carry)

        kernel_env("fused_lstm", "interpret")
        monkeypatch.setattr(
            fused_lstm, "_probe_cell",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("forced probe failure")))
        y_f, _ = layer.apply_with_carry(params, x, carry)
        np.testing.assert_array_equal(np.asarray(y_f), np.asarray(y_ref))
        snap = default_kernel_registry().snapshot()["fused_lstm"]
        assert any(not v["enabled"] and "forced probe failure"
                   in v["reason"] for v in snap.values())
        assert any(e["kind"] == "kernel_fallback"
                   for e in flight.default_flight_recorder().events())

    def test_exotic_activation_stays_on_reference(self):
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM

        layer = LSTM(n_out=16, n_in=8, activation="relu")
        assert fused_lstm.cell_for(layer, jnp.float32) is None


# ==========================================================================
# LSTM decode cell path (PR 9 residue: engine decode reuses the cell)
# ==========================================================================
class TestLSTMDecodeCellPath:
    def _model(self):
        from deeplearning4j_tpu.models.textgen_lstm import (
            TextGenerationLSTM,
        )

        return TextGenerationLSTM(num_classes=23, units=32,
                                  max_length=16).init()

    def _run(self, model, cell_path, n_req=4):
        from deeplearning4j_tpu.serving.generate import GenerationEngine

        eng = GenerationEngine(model, n_slots=3, max_length=48,
                               decode_cell_path=cell_path,
                               default_timeout_s=120.0)
        used_cell = eng.backend.cell_path
        eng.warmup()
        before = dict(eng.trace_counts)
        prompts = [np.random.default_rng(i).integers(0, 23, (6 + i,))
                   .astype(np.int32) for i in range(n_req)]
        outs = [eng.generate(p, max_new=10) for p in prompts]
        retraces = {k: eng.trace_counts.get(k, 0) - before.get(k, 0)
                    for k in eng.trace_counts}
        eng.shutdown()
        return outs, retraces, used_cell

    def test_cell_path_bit_identical_and_zero_retraces(self):
        model = self._model()
        o_legacy, r_legacy, used_l = self._run(model, False)
        o_cell, r_cell, used_c = self._run(model, True)
        assert not used_l and used_c
        for a, b in zip(o_legacy, o_cell):
            np.testing.assert_array_equal(a, b)
        # the satellite's retrace guard: 0 steady-state recompiles with
        # the cell path AND with the fallback
        assert all(v == 0 for v in r_legacy.values()), r_legacy
        assert all(v == 0 for v in r_cell.values()), r_cell

    def test_cell_path_with_fused_kernel_interpret(self, kernel_env):
        model = self._model()
        o_ref, _, _ = self._run(model, True)
        kernel_env("fused_lstm", "interpret")
        o_k, r_k, used = self._run(model, True)
        assert used
        assert all(v == 0 for v in r_k.values()), r_k
        # greedy decode through the interpret kernel stays bit-identical
        # (cell forward is bit-exact at fp32)
        for a, b in zip(o_ref, o_k):
            np.testing.assert_array_equal(a, b)

    def test_describe_reports_cell_path(self):
        from deeplearning4j_tpu.serving.generate import GenerationEngine

        eng = GenerationEngine(self._model(), n_slots=2, max_length=32)
        try:
            assert eng.describe()["decode_cell_path"] is True
        finally:
            eng.shutdown()

    def test_unsupported_stack_falls_back_to_forward_path(self):
        from deeplearning4j_tpu.serving.generate import (
            _cell_decode_supported,
        )
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import (
            GravesBidirectionalLSTM,
            RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(GravesBidirectionalLSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(5)).build())
        net = MultiLayerNetwork(conf).init()
        assert not _cell_decode_supported(net)


# ==========================================================================
# fused ZeRO-1 update
# ==========================================================================
class TestFusedZero1:
    def _build(self, seed=7):
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import (
            DenseLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import Adam

        # 13→30→7: 637 total elements, NOT divisible by the 8 shards →
        # the flat shard carries real zero-padding (odd-count parity)
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(1e-2)).weight_init("xavier").list()
                .layer(DenseLayer(n_out=30, activation="relu"))
                .layer(OutputLayer(n_out=7, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(13)).build())
        return MultiLayerNetwork(conf).init()

    def _run_steps(self, fused, steps=4):
        from deeplearning4j_tpu.parallel import zero
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh

        mesh = TrainingMesh(data=8)
        net = self._build()
        step, layout = zero.make_sharded_train_step(net, mesh,
                                                    fused_update=fused)
        assert layout.n_padding() > 0  # the odd-count case is real
        zopt = zero.shard_model_opt_state(net, layout, mesh=mesh.mesh)
        params, state = net.params_, net.state_
        rng = np.random.default_rng(0)
        X = rng.standard_normal((16, 13)).astype(np.float32)
        y = np.eye(7, dtype=np.float32)[rng.integers(0, 7, 16)]
        for it in range(steps):
            params, zopt, state, score = step(
                params, zopt, state, jnp.asarray(X), jnp.asarray(y),
                None, None, jax.random.PRNGKey(0),
                jnp.asarray(it, jnp.int32), jnp.asarray(0, jnp.int32))
        return params, zopt

    def test_fused_bit_exact_params_and_slots(self, kernel_env):
        p_ref, z_ref = self._run_steps(False)
        kernel_env("fused_zero1", "interpret")
        p_f, z_f = self._run_steps(None)
        snap = default_kernel_registry().snapshot().get("fused_zero1", {})
        assert any(v["enabled"] for v in snap.values())
        for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                        jax.tree_util.tree_leaves(p_f)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(z_ref),
                        jax.tree_util.tree_leaves(z_f)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_forced_probe_failure_falls_back_identical(self, kernel_env,
                                                       monkeypatch):
        p_ref, z_ref = self._run_steps(False)
        kernel_env("fused_zero1", "interpret")
        monkeypatch.setattr(
            fused_update, "_probe_group",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("forced zero1 probe failure")))
        p_f, z_f = self._run_steps(None)
        for a, b in zip(jax.tree_util.tree_leaves((p_ref, z_ref)),
                        jax.tree_util.tree_leaves((p_f, z_f))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        snap = default_kernel_registry().snapshot()["fused_zero1"]
        assert any("forced zero1 probe failure" in v["reason"]
                   for v in snap.values())

    def test_non_adam_groups_stay_on_reference(self, kernel_env):
        from deeplearning4j_tpu.parallel.zero import build_layout
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import RmsProp

        kernel_env("fused_zero1", "interpret")
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(RmsProp(1e-2)).list()
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        layout = build_layout(net, 4)
        impls = fused_update.resolve_group_impls(layout, None)
        assert impls == [None] * len(layout.groups)

    def test_fused_adam_apply_padding_lanes_stay_zero(self):
        # 3 × 100 elements: the kernel pads to full (rows, 128) tiles —
        # padded lanes must come back zero (they are sliced off, but the
        # invariant is what makes the bit-parity argument local)
        p = jnp.asarray(_rand((3, 100), 0))
        g = jnp.asarray(_rand((3, 100), 1))
        m = jnp.asarray(_rand((3, 100), 2))
        v = jnp.abs(jnp.asarray(_rand((3, 100), 3)))
        new_p, m2, v2 = jax.jit(lambda *a: fused_update.fused_adam_apply(
            *a, b1=0.9, b2=0.999, eps=1e-8, interpret=True))(
            p, g, m, v, jnp.asarray(0.01, jnp.float32))
        ref_m = jax.jit(lambda m, g: 0.9 * m + (1.0 - 0.9) * g)(m, g)
        np.testing.assert_array_equal(np.asarray(m2), np.asarray(ref_m))
        assert new_p.shape == (3, 100)


# ==========================================================================
# int8 serving matmul
# ==========================================================================
class TestInt8Matmul:
    def test_quantization_error_bound(self):
        w = _rand((64, 32), 0)
        q, s = int8_matmul.quantize_int8(w)
        assert q.dtype == np.int8 and s.shape == (32,)
        err = np.abs(w - q.astype(np.float32) * s)
        assert np.all(err <= s / 2 + 1e-9)  # round-to-nearest bound

    def test_kernel_bit_exact_vs_reference_fp32(self):
        x = jnp.asarray(_rand((8, 100), 1))
        q, s = int8_matmul.quantize_int8(_rand((100, 40), 2) * 0.2)
        got = int8_matmul.int8_matmul(x, jnp.asarray(q), jnp.asarray(s),
                                      interpret=True)
        want = int8_matmul.int8_matmul_reference(x, jnp.asarray(q),
                                                 jnp.asarray(s))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_rank3_head(self):
        x = jnp.asarray(_rand((2, 5, 16), 0))
        q, s = int8_matmul.quantize_int8(_rand((16, 9), 1))
        params = {"W_q8": jnp.asarray(q), "W_scale": jnp.asarray(s)}
        y = int8_matmul.serving_matmul(params, x)
        assert y.shape == (2, 5, 9)

    def _trained_net(self):
        from deeplearning4j_tpu.nn.conf import (
            InputType,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import (
            DenseLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(1e-3)).weight_init("xavier").list()
                .layer(DenseLayer(n_out=64, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(32)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        X = rng.standard_normal((120, 32)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 120)]
        for _ in range(20):
            net.fit(X, y)
        return net, X

    def test_engine_int8_top1_agreement_and_fp32_untouched(self):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        net, X = self._trained_net()
        e_f32 = InferenceEngine(net)
        e_i8 = InferenceEngine(net, int8_serving=True)
        a = e_f32.infer(X[:64])
        b = e_i8.infer(X[:64])
        agree = np.mean(np.argmax(a, 1) == np.argmax(b, 1))
        assert agree >= 0.99
        # documented tolerance: probabilities move by the per-channel
        # quantization error, not more
        assert np.max(np.abs(a - b)) < 0.05
        # the MODEL keeps fp32 weights (training/checkpoints never see q8)
        assert "W" in net.params_[0] and "W_q8" not in net.params_[0]
        rep = e_i8.int8_report
        assert rep["layers_quantized"] == 2
        assert rep["weight_bytes_int8"] < 0.3 * rep["weight_bytes_fp32"]
        assert e_i8.describe()["int8_serving"] is True

    def test_zoo_model_int8_serving_top1(self):
        """The ISSUE's zoo-model oracle: serve a zoo architecture's
        heads int8-quantized; top-1 must agree with fp32 serving."""
        from deeplearning4j_tpu.models.lenet import LeNet
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        assert LeNet.serving_int8  # hint: heads tolerate quantization
        net = LeNet(num_classes=10).init()
        rng = np.random.default_rng(0)
        X = rng.standard_normal((60, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 60)]
        for _ in range(6):
            net.fit(X, y)
        a = InferenceEngine(net).infer(X[:32])
        e_i8 = InferenceEngine(net, int8_serving=True)
        b = e_i8.infer(X[:32])
        assert e_i8.int8_report["layers_quantized"] >= 1
        assert np.mean(np.argmax(a, 1) == np.argmax(b, 1)) >= 0.99

    def test_engine_kernel_interpret_vs_fallback(self, kernel_env):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        net, X = self._trained_net()
        b_ref = InferenceEngine(net, int8_serving=True).infer(X[:16])
        kernel_env("int8_matmul", "interpret")
        e_k = InferenceEngine(net, int8_serving=True)
        b_k = e_k.infer(X[:16])
        snap = default_kernel_registry().snapshot().get("int8_matmul", {})
        assert any(v["enabled"] for v in snap.values())
        np.testing.assert_array_equal(b_ref, b_k)  # same expression

    def test_forced_probe_failure_serves_reference(self, kernel_env,
                                                   monkeypatch):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        net, X = self._trained_net()
        b_ref = InferenceEngine(net, int8_serving=True).infer(X[:16])
        kernel_env("int8_matmul", "interpret")
        monkeypatch.setattr(
            int8_matmul, "_probe_int8",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("forced int8 probe failure")))
        b_f = InferenceEngine(net, int8_serving=True).infer(X[:16])
        np.testing.assert_array_equal(b_ref, b_f)

    def test_memory_estimator_int8_bytes(self):
        from deeplearning4j_tpu.nn.conf.memory import memory_report_mln

        net, _ = self._trained_net()
        rep = memory_report_mln(net.conf)
        f32 = rep.total_memory_bytes(32, training=False)
        i8 = rep.total_memory_bytes(32, training=False, int8_weights=True)
        assert i8 < f32
        # training bytes never change — int8 is serving-only
        assert rep.total_memory_bytes(32, training=True) == \
            rep.total_memory_bytes(32, training=True)
        w_elems = 32 * 64 + 64 * 10
        assert f32 - i8 == pytest.approx(3 * w_elems - 4 * (64 + 10),
                                         abs=8)

    def test_generic_engine_rejects_int8(self):
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        class Opaque:
            def output(self, x, mask=None):
                return np.asarray(x)

        with pytest.raises(TypeError):
            InferenceEngine(Opaque(), int8_serving=True)

    def test_reload_to_layerless_model_fails_typed(self):
        """The int8 guard must also cover models arriving via hot
        reload, not just __init__ — a layer-less checkpoint must fail
        typed, not AttributeError mid-swap."""
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        net, _ = self._trained_net()
        eng = InferenceEngine(net, int8_serving=True)

        class Opaque:
            def output(self, x, mask=None):
                return np.asarray(x)

        with pytest.raises(TypeError, match="generic output path"):
            eng._quantize_params(Opaque())


# ==========================================================================
# Mosaic-compiled variants — real TPU only (the tunnel is absent in
# tier-1; these are the kernels' compiled-path gates for verify runs)
# ==========================================================================
@pytest.mark.slow
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Mosaic-compiled kernel variants need the TPU "
                           "backend (axon)")
class TestMosaicCompiled:
    def test_fused_lstm_probe_compiles(self):
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM

        default_kernel_registry().reset("fused_lstm")
        layer = LSTM(n_out=256, n_in=128, activation="tanh")
        assert fused_lstm.cell_for(layer, jnp.float32) is not None

    def test_int8_probe_compiles(self):
        default_kernel_registry().reset("int8_matmul")
        impl = int8_matmul._impl_for(512, 512, jnp.float32)
        assert impl is not int8_matmul.int8_matmul_reference
