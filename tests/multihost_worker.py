"""Worker script for the multi-host parity test — launched as a separate
process per "host" by tests/test_multihost.py.

Usage: python multihost_worker.py <coordinator> <num_procs> <pid> <outdir>

Trains LeNet-ish CNN on a deterministic synthetic stream via the
MultiHostNetwork facade (2 local CPU devices per process → 4 global) and
dumps final params + scores for the parent to compare against
single-process training. Port of the reference parity test
``TestCompareParameterAveragingSparkVsSingleMachine.java`` (SURVEY.md §4.5:
distributed-vs-single-machine parameter equality).
"""

import os
import sys

coordinator, nprocs, pid, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel.multihost import (  # noqa: E402
    MultiHostNetwork,
    ParameterAveragingTrainingMaster,
    ShardedDataSetIterator,
    initialize,
)
from tests.multihost_model import build_net, global_batches  # noqa: E402

ctx = initialize(coordinator, num_processes=nprocs, process_id=pid)
assert len(jax.devices()) == 2 * nprocs, jax.devices()

net = build_net()
master = ParameterAveragingTrainingMaster.Builder().collect_training_stats(True).build()
facade = MultiHostNetwork(net, master, ctx)

it = ShardedDataSetIterator(global_batches(), nprocs, pid)
facade.fit(it, epochs=2)

# checkpoint-restart exercise: chief saves, everyone restores, state intact
ckpt = os.path.join(outdir, "mh_ckpt.zip")
facade.save_checkpoint(ckpt)
facade.restore_checkpoint(ckpt)
it.reset()
facade.fit(it, epochs=1)

# distributed evaluation: each host evaluates its local shard; merged
# result must be identical on every host (reference IEvaluateFlatMap +
# reduce semantics)
eval_it = ShardedDataSetIterator(global_batches(), nprocs, pid)
ev = facade.evaluate(eval_it)
acc = ev.accuracy()
total = int(np.asarray(ev.confusion.matrix).sum())

if pid == 0:
    np.savez(
        os.path.join(outdir, "multihost_result.npz"),
        params=net.params_flat(),
        score=float(net.score_),
        iteration=net.iteration,
        n_stats=len(master.stats),
        eval_accuracy=acc,
        eval_total=total,
    )
else:
    np.savez(
        os.path.join(outdir, f"multihost_result_{pid}.npz"),
        eval_accuracy=acc,
        eval_total=total,
    )
print(f"worker {pid}: done, iteration={net.iteration}", flush=True)
