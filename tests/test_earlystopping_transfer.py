"""Early stopping + transfer learning + memory report tests.

Models the reference suites ``earlystopping/TestEarlyStopping.java`` and
``nn/transferlearning/*`` tests (SURVEY.md §4.2).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.conf.memory import memory_report_mln
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.updaters import Adam, Sgd


def _toy_data(n=64, n_in=4, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    w = rng.standard_normal((n_in, n_out))
    y = np.eye(n_out, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def _net(n_in=4, n_out=3, lr=0.1, seed=12345):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(lr))
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(n_in))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestEarlyStopping:
    def test_max_epochs_termination(self):
        ds = _toy_data()
        train_it = ListDataSetIterator(ds, 16)
        val_it = ListDataSetIterator(_toy_data(seed=1), 16)
        net = _net()
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(val_it))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
            .model_saver(InMemoryModelSaver())
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.termination_reason == "EpochTerminationCondition"
        assert "MaxEpochs" in result.termination_details
        assert result.total_epochs == 5
        assert len(result.score_vs_epoch) == 5
        assert result.get_best_model() is not None
        # best score should be one of the recorded scores
        assert result.best_model_score in result.score_vs_epoch.values()

    def test_score_improvement_patience(self):
        ds = _toy_data()
        train_it = ListDataSetIterator(ds, 16)
        val_it = ListDataSetIterator(ds, 16)
        # lr=0 → no improvement ever → patience trips quickly
        net = _net(lr=0.0)
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(val_it))
            .epoch_termination_conditions(
                ScoreImprovementEpochTerminationCondition(2),
                MaxEpochsTerminationCondition(50),
            )
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.termination_reason == "EpochTerminationCondition"
        assert "ScoreImprovement" in result.termination_details
        assert result.total_epochs <= 5

    def test_max_score_iteration_divergence_guard(self):
        ds = _toy_data()
        train_it = ListDataSetIterator(ds, 16)
        val_it = ListDataSetIterator(ds, 16)
        net = _net()
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(val_it))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(10))
            .iteration_termination_conditions(
                MaxScoreIterationTerminationCondition(1e-8)  # triggers at once
            )
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.termination_reason == "IterationTerminationCondition"
        assert "MaxScore" in result.termination_details

    def test_max_time_termination(self):
        ds = _toy_data()
        train_it = ListDataSetIterator(ds, 8)
        val_it = ListDataSetIterator(ds, 16)
        net = _net()
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(val_it))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(100000))
            .iteration_termination_conditions(
                MaxTimeIterationTerminationCondition(0.0)
            )
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        assert result.termination_reason == "IterationTerminationCondition"

    def test_training_actually_improves_and_best_model_kept(self):
        ds = _toy_data(n=128)
        train_it = ListDataSetIterator(ds, 32)
        val_it = ListDataSetIterator(ds, 64)
        net = _net(lr=0.05)
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(val_it))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(8))
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, train_it).fit()
        scores = [result.score_vs_epoch[e] for e in sorted(result.score_vs_epoch)]
        assert scores[-1] < scores[0]  # learning happened
        best = result.get_best_model()
        # best model's val loss matches recorded best
        got = DataSetLossCalculator(val_it).calculate_score(best)
        assert got == pytest.approx(result.best_model_score, rel=1e-3)


class TestTransferLearning:
    def test_freeze_and_replace_output(self):
        src = _net()
        src.fit(_toy_data(), epochs=2)
        frozen_w_before = np.asarray(src.params_[0]["W"]).copy()

        net2 = (
            TransferLearning.Builder(src)
            .fine_tune_configuration(
                FineTuneConfiguration.Builder().updater(Sgd(0.3)).build()
            )
            .set_feature_extractor(0)
            .nout_replace(1, 5, weight_init="xavier")
            .build()
        )
        assert isinstance(net2.layers[0], FrozenLayer)
        assert net2.layers[1].n_out == 5
        # frozen layer params copied from source
        np.testing.assert_array_equal(np.asarray(net2.params_[0]["W"]), frozen_w_before)
        # train on 5-class data; frozen layer must not move
        ds5 = _toy_data(n_out=5, seed=3)
        net2.fit(ds5, epochs=2)
        np.testing.assert_array_equal(np.asarray(net2.params_[0]["W"]), frozen_w_before)
        out = net2.output(ds5.features)
        assert out.shape == (64, 5)

    def test_remove_and_add_layers(self):
        src = _net()
        src.fit(_toy_data(), epochs=1)
        net2 = (
            TransferLearning.Builder(src)
            .remove_output_layer()
            .add_layer(DenseLayer(n_out=8, activation="relu"))
            .add_layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build()
        )
        assert len(net2.layers) == 3
        # first layer params kept from source (not frozen — compare pre-fit)
        np.testing.assert_array_equal(
            np.asarray(net2.params_[0]["W"]), np.asarray(src.params_[0]["W"])
        )
        ds2 = _toy_data(n_out=2, seed=4)
        net2.fit(ds2, epochs=1)
        assert net2.output(ds2.features).shape == (64, 2)

    def test_fine_tune_only(self):
        src = _net()
        src.fit(_toy_data(), epochs=1)
        ftc = FineTuneConfiguration.Builder().updater(Sgd(0.01)).l2(1e-4).build()
        net2 = TransferLearning.Builder(src).fine_tune_configuration(ftc).build()
        # params preserved exactly
        np.testing.assert_array_equal(
            np.asarray(net2.params_[1]["W"]), np.asarray(src.params_[1]["W"])
        )
        # updater overridden
        assert type(net2.layers[0].updater).__name__ == "Sgd"
        net2.fit(_toy_data(), epochs=1)  # trains fine

    def test_helper_featurize(self):
        src = _net()
        src.fit(_toy_data(), epochs=1)
        net2 = (
            TransferLearning.Builder(src).set_feature_extractor(0).build()
        )
        helper = TransferLearningHelper(net2)
        ds = _toy_data(seed=5)
        feat = helper.featurize(ds)
        assert feat.features.shape == (64, 16)  # dense-16 output
        helper.fit_featurized(feat, epochs=1)
        # tail trained; full-net output consistent with tail output on features
        full_out = net2.output(ds.features)
        tail_out = helper.output_from_featurized(feat.features)
        np.testing.assert_allclose(full_out, tail_out, rtol=1e-5, atol=1e-6)


class TestTransferLearningGraph:
    def test_graph_freeze_and_new_output(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        gb = (
            NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(0.05))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d1", DenseLayer(n_out=10, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "d1")
            .set_outputs("out")
        )
        src = ComputationGraph(gb.build()).init()
        ds = _toy_data()
        src.fit(ds, epochs=1)
        w_before = np.asarray(src.params_["d1"]["W"]).copy()

        net2 = (
            TransferLearning.GraphBuilder(src)
            .set_feature_extractor("d1")
            .nout_replace("out", 6)
            .build()
        )
        np.testing.assert_array_equal(np.asarray(net2.params_["d1"]["W"]), w_before)
        ds6 = _toy_data(n_out=6, seed=9)
        net2.fit(ds6, epochs=2)
        np.testing.assert_array_equal(np.asarray(net2.params_["d1"]["W"]), w_before)
        out = net2.output_single(ds6.features)
        assert out.shape == (64, 6)


class TestMemoryReport:
    def test_mln_report(self):
        net = _net()
        rep = memory_report_mln(net.conf)
        assert rep.total_params == net.num_params()
        b32 = rep.total_memory_bytes(32, training=True)
        b1 = rep.total_memory_bytes(1, training=True)
        assert b32 > b1  # activation term scales with batch
        inf = rep.total_memory_bytes(32, training=False)
        assert inf < b32  # no grads/updater state at inference
        s = rep.to_string(32)
        assert "total params" in s


class TestScoreCalculators:
    """Regression tests for calculator/metric API wiring."""

    def test_roc_classification_regression_autoencoder_calculators(self):
        import jax

        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder
        from deeplearning4j_tpu.train.earlystopping import (
            AutoencoderScoreCalculator,
            ClassificationScoreCalculator,
            RegressionScoreCalculator,
            ROCScoreCalculator,
            VAEReconErrorScoreCalculator,
        )

        ds = _toy_data(n_out=2, seed=0)
        it = ListDataSetIterator(ds, 32)
        net = _net(n_out=2)
        net.fit(ds, epochs=1)
        assert 0.0 <= ClassificationScoreCalculator("accuracy", it).calculate_score(net) <= 1.0
        assert 0.0 <= ROCScoreCalculator(it, "auc").calculate_score(net) <= 1.0
        assert 0.0 <= ROCScoreCalculator(it, "auprc").calculate_score(net) <= 1.0

        # regression net
        rconf = (
            NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="identity", loss="mse"))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork as MLN

        rnet = MLN(rconf).init()
        ds3 = _toy_data(seed=1)
        rit = ListDataSetIterator(ds3, 32)
        for m in ("mse", "mae"):
            v = RegressionScoreCalculator(m, rit).calculate_score(rnet)
            assert np.isfinite(v)

        # autoencoder reconstruct path
        aconf = (
            NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(AutoEncoder(n_out=3, activation="sigmoid"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        anet = MLN(aconf).init()
        for calc_cls in (AutoencoderScoreCalculator, VAEReconErrorScoreCalculator):
            v = calc_cls("mse", ListDataSetIterator(ds, 32)).calculate_score(anet)
            assert np.isfinite(v)

    def test_max_epochs_exact_with_sparse_evaluation(self):
        """MaxEpochs must not overshoot when evaluate_every_n_epochs > 1."""
        ds = _toy_data()
        net = _net()
        cfg = (
            EarlyStoppingConfiguration.Builder()
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(ds, 64)))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
            .evaluate_every_n_epochs(2)
            .build()
        )
        result = EarlyStoppingTrainer(cfg, net, ListDataSetIterator(ds, 16)).fit()
        assert result.total_epochs == 4


class TestErrorTermination:
    def test_exception_during_fit_returns_error_reason(self):
        """Reference parity: BaseEarlyStoppingTrainer catches training
        exceptions and returns TerminationReason.Error instead of raising."""
        net = _net()
        ds = _toy_data()
        it = ListDataSetIterator(ds, 16)

        class ExplodingIterator:
            def __iter__(self):
                raise RuntimeError("boom: injected data failure")

            def reset(self):
                pass

            def async_supported(self):
                return False

        cfg = (
            EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
            .score_calculator(DataSetLossCalculator(it))
            .model_saver(InMemoryModelSaver())
            .build()
        )
        trainer = EarlyStoppingTrainer(cfg, net, ExplodingIterator())
        result = trainer.fit()
        assert result.termination_reason == "Error"
        assert "boom" in result.termination_details
